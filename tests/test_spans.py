"""Tracing-plane tests (obs.spans / trace_export / watchdog +
wire-through): tracer semantics, Perfetto export + validation,
trace_report aggregation/diff, the bit-identical-decisions contract on
the queue and the guarded epoch runner, and the supervisor span_log's
crash survival."""

import importlib.util
import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from dmclock_tpu.obs import spans as S
from dmclock_tpu.obs import trace_export as TE
from dmclock_tpu.obs.registry import MetricsRegistry, publish_span_gauges
from dmclock_tpu.obs.watchdog import Watchdog

REPO = Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "trace_report", REPO / "scripts" / "trace_report.py")
trace_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(trace_report)


def make_clock(start=0):
    """Deterministic injectable ns clock."""
    state = {"t": start}

    def clock():
        return state["t"]

    def advance(ns):
        state["t"] += ns

    return clock, advance


class TestSpanTracer:
    def test_nesting_self_time(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        with tr.span("outer", "host_prep"):
            adv(10)
            with tr.span("inner", "dispatch"):
                adv(30)
            adv(5)
        rows = tr.rows()
        assert [r["name"] for r in rows] == ["inner", "outer"]
        inner, outer = rows
        assert inner["dur"] == 30 and inner["self"] == 30
        assert inner["depth"] == 1
        assert outer["dur"] == 45 and outer["self"] == 15
        cats = tr.category_totals()
        assert cats["host_prep"] == 15 and cats["dispatch"] == 30

    def test_instant_and_args(self):
        tr = S.SpanTracer()
        tr.instant("mark", "retry", error="Boom")
        (row,) = tr.rows()
        assert row["dur"] == 0 and row["args"] == {"error": "Boom"}

    def test_unknown_category_rejected(self):
        # ValueError, not assert: must survive PYTHONOPTIMIZE
        tr = S.SpanTracer()
        with pytest.raises(ValueError, match="taxonomy"):
            tr.span("x", "not-a-category")
        with pytest.raises(ValueError, match="taxonomy"):
            tr.instant("x", "also-wrong")

    def test_null_guard_is_noop(self):
        with S.span(None, "x", "dispatch"):
            pass
        S.instant(None, "x", "retry")   # no raise, nothing recorded

    def test_ring_bound_drops_oldest_keeps_aggregates(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(limit=4, clock_ns=clock)
        for i in range(10):
            with tr.span(f"s{i}", "drain"):
                adv(7)
        assert len(tr.rows()) == 4
        assert tr.spans_recorded == 10
        assert tr.spans_dropped == 6
        # aggregates are exact past the wrap
        assert tr.category_totals()["drain"] == 70
        assert tr.category_counts()["drain"] == 10

    def test_thread_safety_and_per_thread_stacks(self):
        tr = S.SpanTracer()

        def worker():
            for _ in range(200):
                with tr.span("w", "fetch"):
                    with tr.span("w2", "drain"):
                        pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert tr.spans_recorded == 4 * 200 * 2
        assert tr.category_counts()["fetch"] == 800
        # depths never interleave across threads
        assert all(r["depth"] == (1 if r["name"] == "w2" else 0)
                   for r in tr.rows())

    def test_drain_jsonl_appends_and_clears(self, tmp_path):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        path = str(tmp_path / "spans.jsonl")
        with tr.span("a", "checkpoint"):
            adv(5)
        assert tr.drain_jsonl(path) == 1
        assert tr.rows() == []
        with tr.span("b", "checkpoint"):
            adv(5)
        assert tr.drain_jsonl(path) == 2 - 1
        rows = S.load_jsonl(path)
        assert [r["name"] for r in rows] == ["a", "b"]

    def test_leaked_child_tolerated_and_counted(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        outer = tr.span("outer", "host_prep")
        inner = tr.span("inner", "dispatch")
        outer.__enter__()
        inner.__enter__()
        adv(10)
        # exiting the OUTER span with the inner still open must not
        # corrupt the stack -- and the lost child is COUNTED
        outer.__exit__(None, None, None)
        assert tr.rows()[-1]["name"] == "outer"
        assert tr.spans_leaked == 1
        # the leaked child's late exit is a discipline break too, not
        # a fabricated second row
        n_rows = len(tr.rows())
        inner.__exit__(None, None, None)
        assert len(tr.rows()) == n_rows
        assert tr.spans_leaked == 2
        with tr.span("next", "fetch"):
            adv(1)
        assert tr.rows()[-1]["depth"] == 0
        assert tr.summary()["leaked"] == 2

    def test_double_exit_counts_not_duplicates(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        sp = tr.span("s", "drain")
        sp.__enter__()
        adv(5)
        sp.__exit__(None, None, None)
        sp.__exit__(None, None, None)
        assert len(tr.rows()) == 1
        assert tr.spans_leaked == 1


class TestChromeExport:
    def _tracer(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        with tr.span("epoch", "host_prep"):
            adv(1000)
            with tr.span("launch", "dispatch"):
                adv(2000)
            with tr.span("wait", "device_compute"):
                adv(5000)
        return tr

    def test_export_validates(self, tmp_path):
        tr = self._tracer()
        path = str(tmp_path / "t.json")
        n = TE.export_chrome_trace(tr, path, metadata={"who": "test"})
        assert n == 3
        stats = TE.validate_chrome_trace(path)
        assert stats["events"] == 3 and stats["tids"] == 1
        # self-time sums match the tracer's category totals (ns)
        for cat, ns in tr.category_totals().items():
            if ns:
                assert stats["cat_self_ns"][cat] == pytest.approx(
                    ns, rel=1e-9)

    def test_export_loads_as_chrome_json(self, tmp_path):
        path = str(tmp_path / "t.json")
        TE.export_chrome_trace(self._tracer(), path)
        obj = json.load(open(path))
        assert {e["ph"] for e in obj["traceEvents"]} == {"X"}
        # sorted by ts; parent-first at equal ts
        ts = [e["ts"] for e in obj["traceEvents"]]
        assert ts == sorted(ts)

    def test_validator_rejects_bad_category(self, tmp_path):
        path = str(tmp_path / "bad.json")
        json.dump({"traceEvents": [
            {"name": "x", "cat": "mystery", "ph": "X", "ts": 0,
             "dur": 1, "pid": 0, "tid": 0}]}, open(path, "w"))
        with pytest.raises(ValueError, match="taxonomy"):
            TE.validate_chrome_trace(path)

    def test_validator_rejects_partial_overlap(self, tmp_path):
        path = str(tmp_path / "overlap.json")
        json.dump({"traceEvents": [
            {"name": "a", "cat": "dispatch", "ph": "X", "ts": 0.0,
             "dur": 10.0, "pid": 0, "tid": 0},
            {"name": "b", "cat": "dispatch", "ph": "X", "ts": 5.0,
             "dur": 10.0, "pid": 0, "tid": 0}]}, open(path, "w"))
        with pytest.raises(ValueError, match="nested"):
            TE.validate_chrome_trace(path)

    def test_validator_rejects_ts_regression(self, tmp_path):
        path = str(tmp_path / "regress.json")
        json.dump({"traceEvents": [
            {"name": "a", "cat": "dispatch", "ph": "X", "ts": 10.0,
             "dur": 1.0, "pid": 0, "tid": 0},
            {"name": "b", "cat": "dispatch", "ph": "X", "ts": 0.0,
             "dur": 1.0, "pid": 0, "tid": 0}]}, open(path, "w"))
        with pytest.raises(ValueError, match="regressed"):
            TE.validate_chrome_trace(path)

    def test_load_rows_roundtrip_both_formats(self, tmp_path):
        tr = self._tracer()
        cj = str(tmp_path / "t.json")
        jl = str(tmp_path / "t.jsonl")
        TE.export_chrome_trace(tr, cj)
        tr.export_jsonl(jl)
        a = TE.load_rows(cj)
        b = TE.load_rows(jl)
        assert len(a) == len(b) == 3
        assert sorted(r["name"] for r in a) == \
            sorted(r["name"] for r in b)
        assert {r["cat"] for r in a} == {r["cat"] for r in b}


class TestTraceReport:
    def _write_trace(self, tmp_path, name="t.json"):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        for _ in range(4):
            with tr.span("round", "dispatch"):
                adv(17_000_000)
            with tr.span("sync", "device_compute"):
                adv(3_000_000)
        path = str(tmp_path / name)
        TE.export_chrome_trace(tr, path)
        return path

    def test_report_table_and_ratio(self, tmp_path, capsys):
        path = self._write_trace(tmp_path)
        assert trace_report.main([path]) == 0
        out = capsys.readouterr().out
        assert "round" in out and "dispatch" in out
        # 4x17ms dispatch vs 4x3ms compute
        assert "dispatch-vs-compute ratio: 5.667" in out
        assert "ns/dec" not in out      # amortized column is opt-in

    def test_report_per_decision_amortized_column(self, tmp_path,
                                                  capsys):
        # --decisions N: the loop-structure-independent cost view
        # when one stream launch covers a whole chunk of rounds
        path = self._write_trace(tmp_path)
        assert trace_report.main([path, "--decisions",
                                  "1000000"]) == 0
        out = capsys.readouterr().out
        assert "ns/dec" in out
        # 4 x 17ms dispatch self over 1M decisions = 68 ns/decision
        assert "dispatch amortized: 68.0 ns/decision" in out

    def test_aggregate_self_time_sweep_on_chrome_rows(self, tmp_path):
        # chrome rows carry no "self": the sweep must subtract
        # children from parents
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        with tr.span("outer", "host_prep"):
            adv(10_000)
            with tr.span("inner", "dispatch"):
                adv(40_000)
        path = str(tmp_path / "n.json")
        TE.export_chrome_trace(tr, path)
        agg = trace_report.aggregate(TE.load_rows(path))
        assert agg[("outer", "host_prep")]["self_ns"] == \
            pytest.approx(10_000)
        assert agg[("inner", "dispatch")]["self_ns"] == \
            pytest.approx(40_000)

    def test_diff_mode(self, tmp_path, capsys):
        a = self._write_trace(tmp_path, "a.json")
        # baseline with a heavier dispatch tax
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        for _ in range(4):
            with tr.span("round", "dispatch"):
                adv(60_000_000)
            with tr.span("sync", "device_compute"):
                adv(3_000_000)
        b = str(tmp_path / "b.json")
        TE.export_chrome_trace(tr, b)
        assert trace_report.main([a, "--diff", b]) == 0
        out = capsys.readouterr().out
        assert "span diff" in out
        assert "-172.00" in out     # 4 x (17-60) ms of dispatch self
        assert "dispatch-vs-compute ratio: 20.000 -> 5.667" in out

    def test_bad_input_exits_2(self, tmp_path, capsys):
        missing = str(tmp_path / "nope.json")
        assert trace_report.main([missing]) == 2


class TestWatchdog:
    def test_dispatch_share_warning(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        logs = []
        reg = MetricsRegistry()
        wd = Watchdog(tr, dispatch_share_warn=0.5, registry=reg,
                      log=logs.append, clock_ns=clock)
        with tr.span("l", "dispatch"):
            adv(90_000_000)
        with tr.span("w", "device_compute"):
            adv(10_000_000)
        warns = wd.poll_once()
        assert [w["kind"] for w in warns] == ["dispatch_share"]
        assert warns[0]["share"] == pytest.approx(0.9)
        assert logs and logs[0].startswith("# watchdog:")
        assert reg.counter(
            "dmclock_watchdog_warnings_total").value == 1
        # still breaching: same episode, no warning spam
        with tr.span("l", "dispatch"):
            adv(90_000_000)
        with tr.span("w", "device_compute"):
            adv(10_000_000)
        assert wd.poll_once() == []
        # healthy window resets the episode...
        with tr.span("l", "dispatch"):
            adv(10_000_000)
        with tr.span("w", "device_compute"):
            adv(90_000_000)
        assert wd.poll_once() == []
        # ...so a fresh breach warns again
        with tr.span("l", "dispatch"):
            adv(90_000_000)
        with tr.span("w", "device_compute"):
            adv(10_000_000)
        assert [w["kind"] for w in wd.poll_once()] == \
            ["dispatch_share"]

    def test_share_not_judged_mid_chain(self):
        # the chained-launch wiring records device time only at chain
        # ends: a poll window with dispatch spans but NO completed
        # device span must not warn (it would fire on every healthy
        # mid-chain poll)
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        wd = Watchdog(tr, dispatch_share_warn=0.5,
                      log=lambda _s: None, clock_ns=clock)
        with tr.span("l", "dispatch"):
            adv(500_000_000)
        assert wd.poll_once() == []
        # the chain-end window (device span completes) IS judged
        with tr.span("l", "dispatch"):
            adv(500_000_000)
        with tr.span("w", "device_compute"):
            adv(100_000_000)
        assert [w["kind"] for w in wd.poll_once()] == \
            ["dispatch_share"]

    def test_skipped_windows_accumulate_into_judged_one(self):
        # mid-chain polls must NOT advance the share baseline: a
        # chain paying 3s dispatch / 1s device across several polls
        # breaches 0.6 even though the final window alone would not
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        wd = Watchdog(tr, dispatch_share_warn=0.6,
                      log=lambda _s: None, clock_ns=clock)
        for _ in range(3):      # mid-chain: dispatch only, skipped
            with tr.span("l", "dispatch"):
                adv(1_000_000_000)
            assert wd.poll_once() == []
        # chain end: 0.5s more dispatch + the 1s digest sync; window
        # = 3.5s dispatch vs 1s device -> share 0.78
        with tr.span("l", "dispatch"):
            adv(500_000_000)
        with tr.span("w", "device_compute"):
            adv(1_000_000_000)
        (w,) = wd.poll_once()
        assert w["kind"] == "dispatch_share"
        assert w["share"] == pytest.approx(3.5 / 4.5, abs=1e-3)

    def test_launch_stall_warns_once_per_episode(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        wd = Watchdog(tr, stall_after_s=1.0, log=lambda _s: None,
                      dispatch_share_warn=2.0,   # share check off:
                      clock_ns=clock)            # stall only
        with tr.span("l", "dispatch"):
            adv(1_000_000)
        assert wd.poll_once() == []          # fresh launch
        adv(2_000_000_000)
        (w,) = wd.poll_once()
        assert w["kind"] == "launch_stall"
        assert wd.poll_once() == []          # same episode: no spam
        with tr.span("l", "dispatch"):       # cadence resumes
            adv(1_000_000)
        assert wd.poll_once() == []
        adv(2_000_000_000)
        assert [w["kind"] for w in wd.poll_once()] == ["launch_stall"]

    def test_no_stall_before_first_launch(self):
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        wd = Watchdog(tr, stall_after_s=1.0, log=lambda _s: None,
                      clock_ns=clock)
        adv(10_000_000_000)
        assert wd.poll_once() == []

    def test_no_stall_while_stream_launch_in_flight(self):
        # the streaming regression (docs/OBSERVABILITY.md): a fused
        # stream chunk legitimately runs for SECONDS inside one
        # launch -- the dispatch span completed long ago, but the
        # host sits inside an open device_wait span.  The watchdog
        # must read the open span as a live cadence, not a stall.
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        wd = Watchdog(tr, stall_after_s=1.0, log=lambda _s: None,
                      dispatch_share_warn=2.0, clock_ns=clock)
        with tr.span("stream.dispatch", "dispatch"):
            adv(1_000_000)
        sp = tr.span("stream.device_wait", "device_compute")
        sp.__enter__()
        adv(5_000_000_000)              # deep inside the fused chunk
        assert wd.poll_once() == [], \
            "launch_stall false-fired on a healthy in-flight chunk"
        sp.__exit__(None, None, None)
        # with the launch closed and no heartbeat, real silence still
        # warns (the fix must not blind the stall check)
        adv(5_000_000_000)
        assert [w["kind"] for w in wd.poll_once()] == ["launch_stall"]

    def test_wedged_launch_still_warns(self):
        # the suppression is BOUNDED: a launch the runtime wedged
        # INSIDE (an open device_wait older than in_flight_max_s)
        # must stop suppressing -- the wedged tunnel is the original
        # failure mode the stall check exists for
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        wd = Watchdog(tr, stall_after_s=1.0, in_flight_max_s=8.0,
                      log=lambda _s: None, dispatch_share_warn=2.0,
                      clock_ns=clock)
        with tr.span("stream.dispatch", "dispatch"):
            adv(1_000_000)
        sp = tr.span("stream.device_wait", "device_compute")
        sp.__enter__()
        adv(5_000_000_000)
        assert wd.poll_once() == []          # young launch: healthy
        adv(5_000_000_000)                   # 10s open > 8s threshold
        assert [w["kind"] for w in wd.poll_once()] == ["launch_stall"]
        sp.__exit__(None, None, None)

    def test_dead_thread_orphan_spans_pruned(self):
        # a thread that exits with a span still open must not report
        # in-flight work forever (it would permanently blind the
        # stall check); its stack prunes on the next walk and the
        # loss is counted
        tr = S.SpanTracer()

        def leaky():
            tr.span("w", "device_compute").__enter__()   # never exits

        t = threading.Thread(target=leaky)
        t.start()
        t.join(5)
        assert tr.open_categories() == {}
        assert tr.oldest_open_ns() is None
        assert tr.spans_leaked >= 1

    def test_no_stall_with_stream_heartbeat(self):
        # the drain-point heartbeat: the stream loop emits a
        # drain-category instant at every chunk drain; recent drain
        # activity proves the serve loop alive between launches
        clock, adv = make_clock()
        tr = S.SpanTracer(clock_ns=clock)
        wd = Watchdog(tr, stall_after_s=1.0, log=lambda _s: None,
                      dispatch_share_warn=2.0, clock_ns=clock)
        with tr.span("stream.dispatch", "dispatch"):
            adv(1_000_000)
        adv(900_000_000)
        tr.instant("stream.heartbeat", "drain", epoch=2)
        adv(900_000_000)                # dispatch silent 1.8s, but the
        assert wd.poll_once() == []     # heartbeat is 0.9s fresh
        adv(2_000_000_000)              # heartbeat stale too: stall
        assert [w["kind"] for w in wd.poll_once()] == ["launch_stall"]

    def test_open_categories_cross_thread(self):
        tr = S.SpanTracer()
        assert tr.open_categories() == {}
        entered = threading.Event()
        release = threading.Event()

        def worker():
            with tr.span("w", "device_compute"):
                entered.set()
                release.wait(5)

        t = threading.Thread(target=worker)
        t.start()
        entered.wait(5)
        with tr.span("d", "dispatch"):
            opens = tr.open_categories()
            assert opens.get("device_compute") == 1
            assert opens.get("dispatch") == 1
        release.set()
        t.join(5)
        assert tr.open_categories() == {}

    def test_thread_lifecycle(self):
        tr = S.SpanTracer()
        wd = Watchdog(tr, interval_s=0.01, log=lambda _s: None)
        with wd:
            time.sleep(0.05)
        assert wd.polls >= 1
        assert wd.poll_errors == 0


class TestSpanGauges:
    def test_publish_span_gauges(self):
        reg = MetricsRegistry()
        publish_span_gauges(reg, {"dispatch_ms_per_launch": 17.25,
                                  "device_ms_per_launch": 3.5,
                                  "host_overhead_frac": 0.81},
                            labels={"workload": "cfg4"})
        text = reg.prometheus()
        assert 'dmclock_dispatch_ms_per_launch{workload="cfg4"} ' \
               '17.25' in text
        assert 'dmclock_host_overhead_frac{workload="cfg4"} 0.81' \
            in text

    def test_partial_summary_publishes_partial(self):
        reg = MetricsRegistry()
        publish_span_gauges(reg, {"dispatch_ms_per_launch": 1.0})
        names = {m.name for m in reg.metrics()}
        assert names == {"dmclock_dispatch_ms_per_launch"}


class TestQueueTracing:
    """Spans through the TPU pull queue: decisions bit-identical with
    tracing on/off, and the decomposition categories all appear."""

    def _drive(self, tracer, spec=0):
        from dmclock_tpu.core.qos import ClientInfo
        from dmclock_tpu.engine.queue import TpuPullPriorityQueue

        q = TpuPullPriorityQueue(
            lambda c: ClientInfo(1.0, 1.0, 0.0), capacity=8,
            speculative_batch=spec, tracer=tracer)
        decs = []
        for t in range(16):
            q.add_request(("r", t), t % 3, time_ns=t * 10 ** 6)
        for t in range(20):
            pr = q.pull_request(now_ns=10 ** 9 + t * 10 ** 6)
            decs.append((pr.type, getattr(pr, "client", None),
                         getattr(pr, "cost", None)))
        return decs

    def test_decisions_bit_identical_and_categories(self):
        tr = S.SpanTracer()
        assert self._drive(None) == self._drive(tr)
        counts = tr.category_counts()
        for cat in ("ingest", "host_prep", "dispatch",
                    "device_compute", "fetch", "drain"):
            assert counts.get(cat, 0) > 0, cat

    def test_speculative_path_traced(self):
        tr = S.SpanTracer()
        assert self._drive(None, spec=4) == self._drive(tr, spec=4)
        assert tr.category_counts().get("dispatch", 0) > 0
        assert tr.category_counts().get("fetch", 0) > 0


class TestGuardedTracing:
    """run_epoch_guarded with a tracer: decisions bit-identical on all
    three epoch engines (the ci.sh tracing gate's in-suite twin)."""

    @pytest.mark.parametrize("engine", ["prefix", "chain", "calendar"])
    def test_digest_identical_with_tracer(self, engine):
        import hashlib

        import jax

        from __graft_entry__ import _preloaded_state
        from dmclock_tpu.robust.guarded import run_epoch_guarded

        def digest(ep):
            h = hashlib.sha256()
            for r in ep.results:
                for name in ("count", "slot", "phase", "cost",
                             "served", "length"):
                    if hasattr(r, name):
                        h.update(np.asarray(
                            jax.device_get(getattr(r, name))
                        ).tobytes())
            return h.hexdigest()

        def run(tracer):
            st = _preloaded_state(256, 8, ring=16)
            return run_epoch_guarded(st, 10 ** 9, engine=engine,
                                     m=2, k=16, tracer=tracer)

        tr = S.SpanTracer()
        ref, traced = run(None), run(tr)
        assert digest(ref) == digest(traced)
        assert ref.count == traced.count
        counts = tr.category_counts()
        # one guarded epoch = one dispatch + one device wait (m
        # batches ride inside the single launch)
        assert counts.get("dispatch", 0) >= 1
        assert counts.get("device_compute", 0) >= 1


class TestSupervisorSpanLog:
    def _job(self, span_log=None):
        from dmclock_tpu.robust.supervisor import EpochJob

        return EpochJob(n=128, depth=8, ring=16, epochs=4, m=2, k=32,
                        ckpt_every=2, span_log=span_log)

    def test_span_log_off_is_bit_identical(self, tmp_path):
        from dmclock_tpu.robust import host_faults as HF
        from dmclock_tpu.robust import supervisor as SV

        ref = SV.run_job(self._job())
        sp = str(tmp_path / "spans.jsonl")
        r1 = SV.run_supervised(self._job(span_log=sp),
                               str(tmp_path / "wd"),
                               HF.zero_host_plan())
        SV.assert_crash_equivalent(r1, ref)
        names = {r["name"] for r in S.load_jsonl(sp)}
        assert {"supervisor.epoch", "supervisor.ingest",
                "supervisor.digest", "supervisor.checkpoint_save",
                "guarded.dispatch",
                "guarded.device_wait"} <= names

    def test_span_stream_survives_kill_and_resume(self, tmp_path):
        from dmclock_tpu.robust import host_faults as HF
        from dmclock_tpu.robust import supervisor as SV

        ref = SV.run_job(self._job())
        sp = str(tmp_path / "spans.jsonl")
        plan = HF.HostFaultPlan(kill_at_decisions=(ref.decisions,))
        r1 = SV.run_supervised(self._job(span_log=sp),
                               str(tmp_path / "wd"), plan)
        SV.assert_crash_equivalent(r1, ref)
        assert r1.restarts == 1
        rows = S.load_jsonl(sp)
        names = [r["name"] for r in rows]
        # the first incarnation's flushed epochs survive AND the
        # second incarnation's resume span is in the stream
        assert names.count("supervisor.resume") == 1
        assert names.count("supervisor.checkpoint_save") >= 2
        # no double counting: replayed epochs appear exactly once
        # (flushes are gated to checkpoint boundaries, so nothing a
        # resume replays was ever flushed by the dead incarnation)
        epochs_seen = [r["args"]["epoch"] for r in rows
                       if r["name"] == "supervisor.epoch"]
        assert sorted(epochs_seen) == sorted(set(epochs_seen))
        # the stream is valid JSONL end to end (load_jsonl validated)
        # and exports to a loadable chrome trace
        out = str(tmp_path / "t.json")
        TE.export_chrome_trace(rows, out)
        json.load(open(out))


class TestClusterTracing:
    def test_run_cluster_rounds_traced_matches_untraced(self):
        import jax.numpy as jnp

        from dmclock_tpu.core.timebase import rate_to_inv_ns
        from dmclock_tpu.parallel import cluster as CL

        S_, C, T, K = 2, 4, 3, 8
        mesh = CL.make_mesh(2)

        def fresh():
            cl = CL.init_cluster(S_, C)
            return CL.shard_cluster(CL.install_clients(
                cl,
                jnp.asarray([rate_to_inv_ns(10.0)] * C, jnp.int64),
                jnp.asarray([rate_to_inv_ns(1.0)] * C, jnp.int64),
                jnp.asarray([0] * C, jnp.int64)), mesh)

        arrivals = np.ones((T, S_, C), dtype=np.int32)
        _, seq0 = CL.run_cluster_rounds(
            fresh(), arrivals, 1, mesh, decisions_per_step=K,
            advance_ns=10 ** 8)
        tr = S.SpanTracer()
        _, seq1 = CL.run_cluster_rounds(
            fresh(), arrivals, 1, mesh, decisions_per_step=K,
            advance_ns=10 ** 8, tracer=tr)
        for a, b in zip(seq0, seq1):
            assert np.array_equal(np.asarray(a.type),
                                  np.asarray(b.type))
            assert np.array_equal(np.asarray(a.slot),
                                  np.asarray(b.slot))
        assert tr.category_counts()["dispatch"] == T
        assert tr.category_counts()["fetch"] == T

    def test_run_with_plan_traced_digest_identical(self):
        import jax.numpy as jnp

        from dmclock_tpu.core.timebase import rate_to_inv_ns
        from dmclock_tpu.parallel import cluster as CL
        from dmclock_tpu.robust import cluster as RC

        S_, C, T, K = 2, 4, 3, 8
        mesh = CL.make_mesh(2)

        def fresh():
            cl = CL.init_cluster(S_, C)
            cl = CL.install_clients(
                cl,
                jnp.asarray([rate_to_inv_ns(10.0)] * C, jnp.int64),
                jnp.asarray([rate_to_inv_ns(1.0)] * C, jnp.int64),
                jnp.asarray([0] * C, jnp.int64))
            return RC.shard_robust(
                RC.init_robust(CL.shard_cluster(cl, mesh)), mesh)

        arrivals = np.ones((T, S_, C), dtype=np.int32)
        _, seq0 = RC.run_with_plan(fresh(), arrivals, 1, mesh, None,
                                   decisions_per_step=K,
                                   advance_ns=10 ** 8)
        tr = S.SpanTracer()
        _, seq1 = RC.run_with_plan(fresh(), arrivals, 1, mesh, None,
                                   decisions_per_step=K,
                                   advance_ns=10 ** 8, tracer=tr)
        assert RC.decision_digest(seq0) == RC.decision_digest(seq1)
        assert tr.category_counts()["dispatch"] == T
