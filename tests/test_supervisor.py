"""Supervised crash-equivalent runs (robust.supervisor;
docs/ROBUSTNESS.md).

The headline gate: a run killed at ANY HostFaultPlan point and
resumed from the rotation checkpoint produces the same
decision-stream digest, final engine state, and metric totals
(modulo the resume rows) as the uninterrupted run -- for all three
epoch engines and both select_impl/calendar_impl fast paths.  Plus
the zero-cost-when-off gate (supervisor-wrapped == bare runner,
bit-identical), the degradation ladder, and bounded restarts."""

import dataclasses

import numpy as np
import pytest

from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.robust import host_faults as HF
from dmclock_tpu.robust import supervisor as SV
from dmclock_tpu.robust.guarded import (LADDER_RUNGS,
                                        DegradationLadder)
from dmclock_tpu.utils import checkpoint as ckpt_mod

# one small job per engine/fast-path combination; module-level cache
# of the bare reference runs (each parametrized case reuses its
# engine's reference instead of re-running it)
ENGINE_JOBS = {
    "prefix-sort": SV.EpochJob(engine="prefix", select_impl="sort"),
    "prefix-radix": SV.EpochJob(engine="prefix", select_impl="radix"),
    "prefix-tag32": SV.EpochJob(engine="prefix", tag_width=32),
    "chain": SV.EpochJob(engine="chain", chain_depth=3, k=32),
    "calendar-minstop": SV.EpochJob(engine="calendar", k=4,
                                    calendar_impl="minstop"),
    "calendar-bucketed": SV.EpochJob(engine="calendar", k=4,
                                     calendar_impl="bucketed",
                                     ladder_levels=2),
    "calendar-wheel": SV.EpochJob(engine="calendar", k=4,
                                  calendar_impl="wheel",
                                  ladder_levels=2),
}
ENGINE_JOBS = {
    name: dataclasses.replace(job, n=96, depth=6, ring=10, epochs=4,
                              m=2, seed=5, arrival_lam=1.0, waves=2,
                              ckpt_every=2)
    for name, job in ENGINE_JOBS.items()
}

_REFS: dict = {}


def ref_of(name: str) -> SV.SupervisedResult:
    if name not in _REFS:
        _REFS[name] = SV.run_job(ENGINE_JOBS[name])
    return _REFS[name]


class TestCrashEquivalence:
    # heavy fast-path cells slow-marked for the tier-1 wall budget
    # (scripts/run_tests.sh runs the full matrix; ci.sh crash smoke
    # covers spawn-mode SIGKILL end to end)
    @pytest.mark.parametrize("name", [
        "prefix-sort", "chain", "calendar-minstop",
        pytest.param("prefix-radix", marks=pytest.mark.slow),
        pytest.param("prefix-tag32", marks=pytest.mark.slow),
        pytest.param("calendar-bucketed", marks=pytest.mark.slow),
        pytest.param("calendar-wheel", marks=pytest.mark.slow),
    ])
    def test_kill_mid_run_resumes_bit_identical(self, tmp_path, name):
        """SIGKILL (trampoline form) between two checkpoints -- the
        resumed run must be bit-identical to the uninterrupted one."""
        job, ref = ENGINE_JOBS[name], ref_of(name)
        assert ref.decisions > 0
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref.decisions // 2, 1),))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1
        # the resume row counts CHECKPOINT resumes only: a kill
        # before the first rotation snapshot replays from scratch
        # (restart without resume) and must read zero there
        assert res.metrics[obsdev.MET_SUPERVISOR_RESUMES] == \
            (1 if res.resumed_from else 0)

    def test_two_kills_two_resumes(self, tmp_path):
        name = "prefix-sort"
        job, ref = ENGINE_JOBS[name], ref_of(name)
        plan = HF.HostFaultPlan(kill_at_decisions=(
            max(ref.decisions // 3, 1), max(2 * ref.decisions // 3, 2)))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 2

    def test_zero_host_fault_gate(self, tmp_path):
        """Supervisor-wrapped run with an EMPTY plan and the ladder
        disabled is bit-identical to the bare runner -- including the
        metric vector, strictly (no resume rows, ladder rows zero)."""
        name = "prefix-sort"
        job, ref = ENGINE_JOBS[name], ref_of(name)
        res = SV.run_supervised(job, tmp_path, HF.zero_host_plan())
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 0
        assert np.array_equal(res.metrics, ref.metrics)
        assert res.metrics[obsdev.MET_LADDER_STEPS] == 0
        assert res.metrics[obsdev.MET_SUPERVISOR_RESUMES] == 0
        assert res.ladder_steps == []

    def test_kill_during_save_lands_on_newest_intact(self, tmp_path):
        """A kill INSIDE the epoch-1 checkpoint save tears that
        snapshot; resume must land on the newest intact entry and
        still pass the digest gate, and the final rotation must end
        on an intact final-epoch snapshot."""
        name = "prefix-sort"
        job, ref = ENGINE_JOBS[name], ref_of(name)
        plan = HF.HostFaultPlan(kill_at_save=((1, "data_renamed"),))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1
        payload, _ = ckpt_mod.restore_pytree_rotating(
            str(tmp_path / "ckpt"), SV._payload_like(job))
        assert int(payload["epoch"]) == job.epochs

    def test_corrupt_save_falls_back_to_older_snapshot(self,
                                                       tmp_path):
        """Epoch-1's save commits then rots on disk; a later kill
        forces a resume that must walk past the corrupt entry (to
        scratch here -- it was the only snapshot) and stay
        bit-identical."""
        name = "prefix-radix"
        job, ref = ENGINE_JOBS[name], ref_of(name)
        plan = HF.HostFaultPlan(
            corrupt_save_at=(1,),
            kill_at_decisions=(max(3 * ref.decisions // 4, 1),))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1

    def test_bounded_restarts_give_up(self, tmp_path):
        name = "prefix-sort"
        job, ref = ENGINE_JOBS[name], ref_of(name)
        points = tuple(max(ref.decisions * (i + 1) // 8, i + 1)
                       for i in range(3))
        plan = HF.HostFaultPlan(kill_at_decisions=points)
        with pytest.raises(SV.SupervisorGaveUp):
            SV.run_supervised(job, tmp_path, plan, max_restarts=1)


TELE_JOB = dataclasses.replace(
    ENGINE_JOBS["calendar-bucketed"], with_hists=True,
    with_ledger=True, flight_records=64)


class TestTelemetryCrashEquivalence:
    """Crash equivalence extends to the telemetry plane: histograms,
    ledger, and the flight ring ride the rotation checkpoints, so a
    killed-and-resumed run's telemetry equals the uninterrupted
    run's bit-for-bit (ISSUE-6 acceptance gate)."""

    def _ref(self):
        if "tele" not in _REFS:
            _REFS["tele"] = SV.run_job(TELE_JOB)
        return _REFS["tele"]

    def test_reference_carries_telemetry(self):
        ref = self._ref()
        assert ref.hists is not None and ref.ledger is not None
        assert ref.hists[:, :-1].sum() > 0
        # device truth: the ledger's ops column covers every decision
        assert ref.ledger[:, 0].sum() == ref.decisions
        assert ref.flight_seq > 0
        from dmclock_tpu.obs import flight as obsflight
        assert ref.flight_buf.shape == (64, obsflight.FLIGHT_COLS)

    def test_kill_mid_run_telemetry_bit_identical(self, tmp_path):
        ref = self._ref()
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref.decisions // 2, 1),))
        res = SV.run_supervised(TELE_JOB, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)   # incl. hists/ledger/
        assert res.restarts == 1               # flight ring + seq

    def test_zero_fault_telemetry_gate(self, tmp_path):
        ref = self._ref()
        res = SV.run_supervised(TELE_JOB, tmp_path,
                                HF.zero_host_plan())
        SV.assert_crash_equivalent(res, ref)
        assert np.array_equal(res.metrics, ref.metrics)

    def test_telemetry_mismatch_is_caught(self):
        """The extended gate actually bites: a perturbed ledger cell
        must fail the assertion."""
        ref = self._ref()
        bad = ref._replace(ledger=ref.ledger.copy())
        bad.ledger[0, 0] += 1
        with pytest.raises(AssertionError, match="ledger"):
            SV.assert_crash_equivalent(bad, ref)

    def test_flight_dump_on_crash(self, tmp_path):
        """A killed incarnation dumps its flight ring (--flight-dump):
        the postmortem record of what the engine was committing when
        the host died."""
        ref = self._ref()
        dump = tmp_path / "flight.jsonl"
        job = dataclasses.replace(TELE_JOB,
                                  flight_dump=str(dump))
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref.decisions // 2, 1),))
        res = SV.run_supervised(job, tmp_path / "wd", plan)
        SV.assert_crash_equivalent(res, ref)
        assert dump.exists(), "crash dump missing"
        import json as _json
        rows = [_json.loads(ln) for ln in
                dump.read_text().splitlines()]
        assert rows, "crash dump empty"
        seqs = [r["seq"] for r in rows]
        assert seqs == sorted(seqs)
        assert all(set(r) == {"seq", "batch", "client", "cls",
                              "tag", "cost", "margin", "gate"}
                   for r in rows)


class TestScrapeLoss:
    def test_scrape_drop_rebinds_and_run_unperturbed(self, tmp_path):
        name = "prefix-sort"
        ref = ref_of(name)
        job = dataclasses.replace(ENGINE_JOBS[name], metrics_port=0)
        plan = HF.HostFaultPlan(drop_scrape_at=(1,))
        res = SV.run_supervised(job, tmp_path, plan)
        # losing (and rebinding) the scrape port is pure telemetry:
        # the decision stream and metrics cannot move
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 0
        assert res.scrape_rebinds >= 1


class TestDegradationLadder:
    def test_rung_order_and_encode_round_trip(self):
        ladder = DegradationLadder(threshold=2)
        cfg = {"calendar_impl": "wheel", "select_impl": "radix",
               "tag_width": 32}
        stepped = []
        for _ in range(12):
            c = ladder.apply(cfg)
            if ladder.note_epoch(c, guard_trips=1):
                stepped.append(ladder.steps[-1].knob)
        assert stepped == [k for k, _, _ in LADDER_RUNGS]
        assert ladder.apply(cfg) == {"calendar_impl": "minstop",
                                     "select_impl": "sort",
                                     "tag_width": 64}
        # fully degraded: nothing left to concede
        assert ladder.note_epoch(ladder.apply(cfg), guard_trips=1) == 0
        clone = DegradationLadder(threshold=2)
        clone.load(ladder.encode())
        assert clone.apply(cfg) == ladder.apply(cfg)

    def test_clean_epochs_reset_the_trip_counter(self):
        ladder = DegradationLadder(threshold=2)
        cfg = {"select_impl": "radix"}
        assert ladder.note_epoch(cfg, guard_trips=1) == 0
        assert ladder.note_epoch(cfg) == 0            # clean: reset
        assert ladder.note_epoch(cfg, guard_trips=1) == 0
        assert ladder.note_epoch(cfg, launch_failures=1) == 1
        assert ladder.steps[0].reason == "launch_failures"

    def test_disabled_ladder_is_inert(self):
        ladder = DegradationLadder(enabled=False)
        cfg = {"select_impl": "radix"}
        for _ in range(5):
            assert ladder.note_epoch(cfg, guard_trips=3) == 0
        assert ladder.apply(cfg) == cfg and ladder.steps_taken == 0

    def test_launch_failure_escalation_steps_down(self, tmp_path,
                                                  monkeypatch):
        """A recoverable error that survives the guarded runner's
        bounded retries is the ladder's launch-failure signal: the
        epoch is re-attempted on the stepped-down exact path instead
        of dying.  Recovered retries are NOT an escalation."""
        calls = []
        real = SV.run_epoch_guarded

        def flaky(state, now, **kw):
            calls.append(kw["select_impl"])
            if kw["select_impl"] == "radix":
                raise TimeoutError("wedged tunnel")
            return real(state, now, **kw)

        monkeypatch.setattr(SV, "run_epoch_guarded", flaky)
        # DEFAULT threshold=2: each failed attempt counts, so the
        # second consecutive failure steps the rung -- the escalation
        # must be reachable without tuning the threshold down
        job = dataclasses.replace(ENGINE_JOBS["prefix-radix"],
                                  ladder=True)
        res = SV.run_supervised(job, tmp_path, HF.zero_host_plan())
        assert [s["knob"] for s in res.ladder_steps] == \
            ["select_impl"]
        assert res.ladder_steps[0]["reason"] == "launch_failures"
        assert res.metrics[obsdev.MET_LADDER_STEPS] == 1
        assert res.restarts == 0          # handled below a restart
        assert calls[:3] == ["radix", "radix", "sort"]

    def test_persistent_error_restarts_then_gives_up(self, tmp_path,
                                                     monkeypatch):
        """With the ladder off (or exhausted), a persistent
        recoverable error is 'the runner died': the trampoline
        restarts from the checkpoint like a kill, bounded by
        max_restarts."""
        def dead(*_a, **_k):
            raise TimeoutError("tunnel never came back")

        monkeypatch.setattr(SV, "run_epoch_guarded", dead)
        with pytest.raises(SV.SupervisorGaveUp):
            SV.run_supervised(ENGINE_JOBS["prefix-sort"], tmp_path,
                              HF.zero_host_plan(), max_restarts=2,
                              backoff_base_s=0.0)

    def test_supervised_tag32_trips_step_down_to_int64(self,
                                                       tmp_path):
        """A real ladder engagement: one client's proportion tag sits
        past the +-2^31 ns rebase window, so every tag32 epoch trips
        and resumes on int64 (guarded contract).  With the ladder on,
        two consecutive trips step tag_width 32 -> 64 -- visible in
        the obs row and the step list -- and the killed+resumed run
        still matches its own uninterrupted reference (ladder
        position rides in the checkpoint)."""
        job = dataclasses.replace(
            ENGINE_JOBS["prefix-tag32"], tag_spread_ns=2 ** 32,
            ladder=True, ladder_threshold=2, epochs=6)
        ref = SV.run_job(job)
        assert ref.metrics[obsdev.MET_REBASE_FALLBACKS] >= 2
        assert ref.metrics[obsdev.MET_LADDER_STEPS] == 1
        assert [s["knob"] for s in ref.ladder_steps] == ["tag_width"]
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref.decisions // 2, 1),))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        # a resumed ladder reloads engaged rungs from the checkpoint
        # (reason reads "resumed"); the POSITION must match exactly
        assert [(s["knob"], s["from"], s["to"])
                for s in res.ladder_steps] == \
            [(s["knob"], s["from"], s["to"])
             for s in ref.ladder_steps]


@pytest.mark.slow
class TestSpawnMode:
    def test_real_sigkill_child_resumes_bit_identical(self, tmp_path,
                                                      monkeypatch):
        """Spawn mode: each incarnation is a child interpreter and the
        plan point is a REAL SIGKILL -- the closest in-repo stand-in
        for the production runner dying mid-bench."""
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        name = "prefix-sort"
        job, ref = ENGINE_JOBS[name], ref_of(name)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref.decisions // 2, 1),))
        res = SV.run_supervised(job, tmp_path, plan, mode="spawn")
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1


# ----------------------------------------------------------------------
# churn (client lifecycle plane) crash equivalence -- docs/LIFECYCLE.md
# ----------------------------------------------------------------------

CHURN_SPEC = None


def _churn_spec() -> dict:
    """Heavy-mechanics churn spec: growth (capacity0=4), eviction
    (life=2 generations), slot recycling (gen2 lands on gen0's
    slots), compaction at every boundary."""
    global CHURN_SPEC
    if CHURN_SPEC is None:
        from dmclock_tpu.lifecycle import make_spec
        CHURN_SPEC = make_spec("churn_storm", total_ids=16,
                               base_lam=1.5, compact_every=1, gens=4,
                               stride=4, life=2, capacity0=4)
    return CHURN_SPEC


def _churn_job(engine: str, loop: str = "round") -> SV.EpochJob:
    return SV.EpochJob(engine=engine, churn=_churn_spec(), epochs=12,
                       m=2, k=8, ring=16, waves=4, ckpt_every=2,
                       seed=11, engine_loop=loop)


def churn_ref(engine: str, loop: str = "round") -> SV.SupervisedResult:
    key = f"churn-{engine}-{loop}"
    if key not in _REFS:
        _REFS[key] = SV.run_job(_churn_job(engine, loop))
    return _REFS[key]


class TestChurnCrashEquivalence:
    """ISSUE-9 acceptance: crash equivalence extends to lifecycle
    state -- SIGKILL mid-churn (including between an admin accept and
    its epoch-boundary application, and mid-compaction) resumes
    bit-identical to the uninterrupted run, slot map + pending-update
    journal + counters included."""

    # one engine per loop stays in the quick sweep; the other four
    # cells are slow-marked for the tier-1 wall budget
    # (scripts/run_tests.sh runs the full matrix)
    @pytest.mark.parametrize("loop,engine", [
        ("round", "prefix"), ("stream", "chain"),
        pytest.param("round", "chain", marks=pytest.mark.slow),
        pytest.param("round", "calendar", marks=pytest.mark.slow),
        pytest.param("stream", "prefix", marks=pytest.mark.slow),
        pytest.param("stream", "calendar", marks=pytest.mark.slow),
    ])
    def test_kill_mid_churn_resumes_bit_identical(self, tmp_path,
                                                  engine, loop):
        job, ref = _churn_job(engine, loop), churn_ref(engine, loop)
        assert ref.decisions > 0
        # the run's own mechanics all fired before/after kill points
        assert ref.lifecycle["grows"] >= 1
        assert ref.lifecycle["compactions"] >= 1
        assert ref.lifecycle["slot_recycles"] >= 1
        plan = HF.HostFaultPlan(kill_at_decisions=(
            max(ref.decisions // 3, 1),
            max(2 * ref.decisions // 3, 2)))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)   # incl. lifecycle
        assert res.restarts == 2

    def test_kill_between_admin_accept_and_apply(self, tmp_path):
        """An op accepted through the control API (WAL-fsynced) whose
        boundary has not come yet must survive the SIGKILL and apply
        EXACTLY once on resume."""
        from dmclock_tpu.lifecycle import wal_append

        job = _churn_job("prefix")
        # client 8 (gen2) registers at boundary 8 -- the same
        # boundary the pinned update applies at (registers are
        # processed before pending control ops within a boundary)
        op = {"op": "update", "cid": 8, "r": 0.0, "w": 8.0, "l": 0.0,
              "apply_at": 8}
        wd_ref = tmp_path / "ref"
        wd_kill = tmp_path / "kill"
        wd_ref.mkdir(), wd_kill.mkdir()
        wal_append(wd_ref, op)
        wal_append(wd_kill, op)
        ref = SV.run_supervised(job, wd_ref, HF.zero_host_plan())
        assert ref.lifecycle["qos_updates"] == 1
        # the uninterrupted CHURN reference without the op diverges:
        # the update visibly changed the decision stream
        assert ref.digest != churn_ref("prefix").digest
        # kill strictly before boundary 8 can have applied the op
        kill_at = max(ref.decisions // 4, 1)
        res = SV.run_supervised(
            job, wd_kill,
            HF.HostFaultPlan(kill_at_decisions=(kill_at,)))
        SV.assert_crash_equivalent(res, ref)
        assert res.lifecycle["qos_updates"] == 1
        assert res.restarts == 1

    def test_kill_mid_compaction(self, tmp_path):
        """SIGKILL between the compaction gather launch and the
        host-side slot-map re-map (the _compact_hook seam): the
        discarded gather must replay cleanly on resume."""
        from dmclock_tpu.lifecycle import plane as plane_mod

        job, ref = _churn_job("prefix"), churn_ref("prefix")
        fired = []

        def hook():
            if not fired:
                fired.append(1)
                raise HF.HostKill("mid-compaction")

        old = plane_mod._compact_hook
        plane_mod._compact_hook = hook
        try:
            res = SV.run_supervised(job, tmp_path,
                                    HF.zero_host_plan())
        finally:
            plane_mod._compact_hook = old
        assert fired, "compaction hook never reached"
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1

    def test_churn_zero_host_fault_gate(self, tmp_path):
        """Supervisor-wrapped churn run with an empty plan == bare
        churn runner, bit-identical including the metric vector and
        the full lifecycle snapshot."""
        job, ref = _churn_job("prefix"), churn_ref("prefix")
        res = SV.run_supervised(job, tmp_path, HF.zero_host_plan())
        SV.assert_crash_equivalent(res, ref)
        assert np.array_equal(res.metrics, ref.metrics)
        assert res.lifecycle == ref.lifecycle

    def test_lifecycle_mismatch_is_caught(self):
        """The extended gate actually bites on lifecycle state."""
        ref = churn_ref("prefix")
        bad = dict(ref.lifecycle)
        bad["evictions"] += 1
        with pytest.raises(AssertionError, match="lifecycle"):
            SV.assert_crash_equivalent(ref._replace(lifecycle=bad),
                                       ref)

    def test_churn_telemetry_rides_the_crash(self, tmp_path):
        """Churn + telemetry: the growing/compacting per-slot ledger
        and the histograms stay bit-identical across a kill."""
        job = dataclasses.replace(_churn_job("prefix"),
                                  with_hists=True, with_ledger=True)
        ref = SV.run_job(job)
        assert ref.ledger is not None
        # the ledger grew with the state arrays (capacity0=4 -> >4)
        assert ref.ledger.shape[0] > 4
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref.decisions // 2, 1),))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)


@pytest.mark.slow
class TestChurnSpawnMode:
    def test_real_sigkill_mid_churn_resumes_bit_identical(
            self, tmp_path, monkeypatch):
        """Spawn mode: the churn job JSON-round-trips into a child
        interpreter, the kill is a REAL SIGKILL, and the resumed run
        (slot map + WAL + journal restored from the rotation
        checkpoint) stays bit-identical."""
        from dmclock_tpu.lifecycle import wal_append

        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        job, ref0 = _churn_job("prefix"), churn_ref("prefix")
        # client 8 (gen2) registers at boundary 8 -- the same
        # boundary the pinned update applies at (registers are
        # processed before pending control ops within a boundary)
        op = {"op": "update", "cid": 8, "r": 0.0, "w": 8.0, "l": 0.0,
              "apply_at": 8}
        wd_ref = tmp_path / "ref"
        wd_kill = tmp_path / "kill"
        wd_ref.mkdir(), wd_kill.mkdir()
        wal_append(wd_ref, op)
        wal_append(wd_kill, op)
        ref = SV.run_supervised(job, wd_ref, HF.zero_host_plan())
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref0.decisions // 2, 1),))
        res = SV.run_supervised(job, wd_kill, plan, mode="spawn")
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1
        assert res.lifecycle["qos_updates"] == 1
