"""Shared helpers for the TPU-engine differential test suites.

Ordering spec being checked everywhere = the oracle's total order
(``core/scheduler.py``), itself pinned to reference
``dmclock_server.h:1115-1186`` by the oracle test suite.
"""

import jax
import jax.numpy as jnp

from dmclock_tpu.core import ReqParams
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import TpuPullPriorityQueue, kernels
from dmclock_tpu.engine.state import EngineState

S = NS_PER_SEC


def assert_states_equal(a: EngineState, b: EngineState):
    for name, x, y in zip(EngineState._fields, a, b):
        assert bool(jnp.array_equal(x, y)), \
            f"state field {name} diverged:\n{x}\nvs\n{y}"


def serial_run(state, now, k, anticipation_ns=0):
    st, _, decs = kernels.engine_run(
        state, jnp.int64(now), k, allow_limit_break=False,
        anticipation_ns=anticipation_ns, advance_now=False)
    return st, jax.device_get(decs)


def build_state(infos, adds, *, capacity=64, ring=64,
                anticipation_ns=0) -> EngineState:
    """EngineState populated via the queue's own ingest path.

    ``adds`` = list of (client, time_ns, cost, delta, rho).
    """
    q = TpuPullPriorityQueue(lambda c: infos[c],
                             anticipation_timeout_ns=anticipation_ns,
                             capacity=capacity, ring_capacity=ring)
    for client, t, cost, delta, rho in adds:
        q.add_request(("r", client, t), client, ReqParams(delta, rho),
                      time_ns=t, cost=cost)
    with q.data_mtx:
        q._flush()
    return q.state


def deep_state(infos, depth, t=1 * S, capacity=64):
    adds = [(c, t, 1, 1, 1) for _ in range(depth) for c in infos]
    return build_state(infos, adds, capacity=capacity)


def starvation_scenario(engine="prefix", engine_loop="round", *,
                        epochs=8, every=2, n=8, ring=32,
                        slo_log=None, flight_dump=None):
    """The provenance plane's seeded limit-starvation scenario (ci.sh
    provenance smoke + tests/test_provenance.py): client 0 is a heavy
    over-limit tenant (high demand, LOW limit ceiling), client 1 a
    well-provisioned competitor, the rest light filler -- client 0's
    delivered rate pins at its limit with backlog queued, so
    ``scripts/explain.py`` must attribute its violating windows to
    ``limit_capped`` from the slo_log + flight dump this writes.

    Runs ``epochs`` guarded epochs (round loop) or chunk launches
    (stream loop) with the SLO window block + provenance block +
    flight ring riding the scans, rolling windows on the ``every``
    grid.  Returns ``(prov, slo_plane, state, now_ns)``.
    """
    import numpy as np

    from dmclock_tpu.core.timebase import rate_to_inv_ns
    from dmclock_tpu.engine import init_state, stream as stream_mod
    from dmclock_tpu.obs import flight as obsflight
    from dmclock_tpu.obs import provenance as obsprov
    from dmclock_tpu.obs import slo as obsslo
    from dmclock_tpu.robust.guarded import (run_epoch_guarded,
                                            run_stream_chunk_guarded)

    dt = 10 ** 8
    st = init_state(n, ring)
    resv = np.zeros(n)
    # client 0: huge weight entitlement but a LOW limit ceiling --
    # the limit, not the proportional race, must be what caps it
    weights = np.asarray([32.0] + [8.0] + [1.0] * (n - 2))
    limits = np.asarray([10.0] + [0.0] * (n - 1))   # client 0 capped

    def inv(rates):
        return jnp.asarray([rate_to_inv_ns(r) for r in rates],
                           jnp.int64)

    st = st._replace(
        active=jnp.ones(n, dtype=bool),
        order=jnp.arange(n, dtype=jnp.int64),
        resv_inv=inv(resv), weight_inv=inv(weights),
        limit_inv=inv(limits))
    # heavy demand for clients 0/1, light filler for the rest, fed
    # through the real superwave ingest so limit tags are the tag
    # algebra's own (head_limit in the future = the gate signal)
    lam = np.asarray([12, 12] + [1] * (n - 2), np.int32)
    rng = np.random.default_rng(5)

    slo_plane = obsslo.SloPlane(n, dt_epoch_ns=dt, ring_depth=64)
    slo_plane.register_from_inv(st.resv_inv, st.weight_inv,
                                st.limit_inv)
    slo_block = slo_plane.stamp(obsslo.window_zero(n))
    prov = obsprov.prov_init(n)
    flight = obsflight.flight_init(256)
    w0 = 0

    def roll(state, e1):
        nonlocal slo_block, w0
        slo_block, closed = slo_plane.roll(slo_block, w0, e1,
                                           depth=state.depth)
        w0 = e1
        if slo_log:
            slo_plane.export_jsonl(slo_log, closed)

    if engine_loop == "stream":
        for e0, b in stream_mod.chunk_bounds(0, epochs, every):
            counts = np.stack([
                np.minimum(rng.poisson(lam), 8).astype(np.int32)
                for _ in range(b - e0)])
            g = run_stream_chunk_guarded(
                st, e0, counts, engine=engine, epochs=b - e0, m=2,
                k=8, chain_depth=3, dt_epoch_ns=dt, waves=8,
                slo=slo_block, prov=prov, flight=flight)
            st, slo_block, prov, flight = (g.state, g.slo, g.prov,
                                           g.flight)
            roll(st, b)
    else:
        ingest = stream_mod.jit_ingest_step(dt_epoch_ns=dt, waves=8)
        for e in range(epochs):
            counts = np.minimum(rng.poisson(lam), 8).astype(np.int32)
            st = ingest(st, jnp.asarray(counts), jnp.int64(e * dt))
            ep = run_epoch_guarded(
                st, (e + 1) * dt, engine=engine, m=2, k=8,
                chain_depth=3, slo=slo_block, prov=prov,
                flight=flight)
            st, slo_block, prov, flight = (ep.state, ep.slo, ep.prov,
                                           ep.flight)
            if (e + 1) % every == 0 or e + 1 == epochs:
                roll(st, e + 1)
    if flight_dump:
        obsflight.flight_dump(flight, flight_dump)
    return prov, slo_plane, st, epochs * dt
