"""Shared helpers for the TPU-engine differential test suites.

Ordering spec being checked everywhere = the oracle's total order
(``core/scheduler.py``), itself pinned to reference
``dmclock_server.h:1115-1186`` by the oracle test suite.
"""

import jax
import jax.numpy as jnp

from dmclock_tpu.core import ReqParams
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import TpuPullPriorityQueue, kernels
from dmclock_tpu.engine.state import EngineState

S = NS_PER_SEC


def assert_states_equal(a: EngineState, b: EngineState):
    for name, x, y in zip(EngineState._fields, a, b):
        assert bool(jnp.array_equal(x, y)), \
            f"state field {name} diverged:\n{x}\nvs\n{y}"


def serial_run(state, now, k, anticipation_ns=0):
    st, _, decs = kernels.engine_run(
        state, jnp.int64(now), k, allow_limit_break=False,
        anticipation_ns=anticipation_ns, advance_now=False)
    return st, jax.device_get(decs)


def build_state(infos, adds, *, capacity=64, ring=64,
                anticipation_ns=0) -> EngineState:
    """EngineState populated via the queue's own ingest path.

    ``adds`` = list of (client, time_ns, cost, delta, rho).
    """
    q = TpuPullPriorityQueue(lambda c: infos[c],
                             anticipation_timeout_ns=anticipation_ns,
                             capacity=capacity, ring_capacity=ring)
    for client, t, cost, delta, rho in adds:
        q.add_request(("r", client, t), client, ReqParams(delta, rho),
                      time_ns=t, cost=cost)
    with q.data_mtx:
        q._flush()
    return q.state


def deep_state(infos, depth, t=1 * S, capacity=64):
    adds = [(c, t, 1, 1, 1) for _ in range(depth) for c in infos]
    return build_state(infos, adds, capacity=capacity)
