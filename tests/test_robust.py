"""Fault injection, graceful degradation, and guarded commits
(docs/ROBUSTNESS.md).

The two load-bearing gates:

1. **Chaos differential** -- an empty / zero-probability ``FaultPlan``
   is bit-identical to no fault plumbing at all, both at cluster scale
   and for all three epoch engines through the guarded wrapper.
2. **Degraded mode** -- with one of four servers down for a window,
   survivors keep their reservation contracts, the restarted server
   re-syncs and resumes, and the ``server_dropouts`` /
   ``tracker_resyncs`` metric rows match the injected plan exactly.
"""

import errno
import functools
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from engine_helpers import S, assert_states_equal, deep_state

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.core.timebase import rate_to_inv_ns
from dmclock_tpu.engine import TpuPullPriorityQueue
from dmclock_tpu.engine.fastpath import (scan_calendar_epoch,
                                         scan_chain_epoch,
                                         scan_prefix_epoch)
from dmclock_tpu.obs import MetricsRegistry, start_http_server
from dmclock_tpu.parallel import cluster as CL
from dmclock_tpu.robust import cluster as RC
from dmclock_tpu.robust import faults as F
from dmclock_tpu.robust.guarded import (retry_with_backoff,
                                        run_epoch_guarded)


# ----------------------------------------------------------------------
# QoS input validation (core.qos satellite)
# ----------------------------------------------------------------------

class TestQosValidation:
    def test_valid_triples_accepted(self):
        ClientInfo(0, 0, 0)
        ClientInfo(10, 1, 0)          # limit 0 = axis disabled
        ClientInfo(10, 1, 10)         # limit == reservation is legal
        ClientInfo(0.5, 2.0, 40.0)

    @pytest.mark.parametrize("axis", range(3))
    def test_nan_rejected(self, axis):
        args = [1.0, 1.0, 2.0]
        args[axis] = float("nan")
        with pytest.raises(ValueError, match="NaN"):
            ClientInfo(*args)

    @pytest.mark.parametrize("axis", range(3))
    def test_negative_rejected(self, axis):
        args = [1.0, 1.0, 2.0]
        args[axis] = -0.5
        with pytest.raises(ValueError, match=">= 0"):
            ClientInfo(*args)

    @pytest.mark.parametrize("axis", range(3))
    def test_infinite_rejected(self, axis):
        args = [1.0, 1.0, 2.0]
        args[axis] = float("inf")
        with pytest.raises(ValueError, match="infinite"):
            ClientInfo(*args)

    def test_limit_below_reservation_rejected(self):
        with pytest.raises(ValueError, match="limit 5.0 < "
                                             "reservation 10.0"):
            ClientInfo(10.0, 1.0, 5.0)

    def test_error_names_the_client(self):
        with pytest.raises(ValueError, match="client 'tenant-7'"):
            ClientInfo(float("nan"), 1.0, 0.0, client="tenant-7")

    def test_update_validates_too(self):
        info = ClientInfo(1.0, 1.0, 2.0, client="c0")
        with pytest.raises(ValueError, match="client 'c0'"):
            info.update(4.0, 1.0, 2.0)   # limit < new reservation
        # the failed update left the old values intact
        assert info.reservation == 1.0 and info.limit == 2.0


# ----------------------------------------------------------------------
# fault plans
# ----------------------------------------------------------------------

class TestFaultPlan:
    def test_zero_plan_is_benign(self):
        plan = F.zero_plan(5, 3)
        assert F.plan_events(plan) == {
            "server_dropouts": 0, "tracker_resyncs": 0,
            "faults_injected": 0}
        assert F.describe(plan) == "none"
        assert F.describe(None) == "none"

    def test_sample_plan_deterministic(self):
        a = F.sample_plan(7, 20, 4, p_dropout=0.3, p_delay=0.2,
                          p_dup=0.2, max_skew_ns=1000)
        b = F.sample_plan(7, 20, 4, p_dropout=0.3, p_delay=0.2,
                          p_dup=0.2, max_skew_ns=1000)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)
        c = F.sample_plan(8, 20, 4, p_dropout=0.3)
        assert not np.array_equal(a.up, c.up)

    def test_single_outage_events(self):
        plan = F.single_outage_plan(6, 4, server=2, down_from=2,
                                    down_until=4)
        ev = F.plan_events(plan)
        assert ev == {"server_dropouts": 1, "tracker_resyncs": 1,
                      "faults_injected": 2}
        assert F.describe(plan).startswith("T6xS4:drop1+resync1")


# ----------------------------------------------------------------------
# cluster-scale chaos differential + degraded mode
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh4():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 (virtual) devices")
    return CL.make_mesh(4)


N_SERVERS, N_CLIENTS, ROUNDS, K = 4, 8, 6, 16
ADVANCE_NS = 10 ** 8     # 0.1 s of virtual time per round
QOS = [(10.0, 1.0 + (i % 3), 0.0) for i in range(N_CLIENTS)]


def _fresh_rc(mesh, tracker_kind="orig"):
    cl = CL.init_cluster(N_SERVERS, N_CLIENTS,
                         tracker_kind=tracker_kind)
    cl = CL.install_clients(
        cl,
        jnp.asarray([rate_to_inv_ns(r) for r, _, _ in QOS], jnp.int64),
        jnp.asarray([rate_to_inv_ns(w) for _, w, _ in QOS], jnp.int64),
        jnp.asarray([rate_to_inv_ns(l) for _, _, l in QOS], jnp.int64))
    cl = CL.shard_cluster(cl, mesh)
    return RC.shard_robust(RC.init_robust(cl), mesh)


def _arrivals():
    return np.ones((ROUNDS, N_SERVERS, N_CLIENTS), dtype=np.int32)


class TestChaosDifferential:
    @pytest.mark.slow
    def test_zero_plan_bit_identical_to_no_plumbing(self, mesh4):
        rc, seq_none = RC.run_with_plan(
            _fresh_rc(mesh4), _arrivals(), 1, mesh4, None,
            decisions_per_step=K, advance_ns=ADVANCE_NS)
        rc2, seq_zero = RC.run_with_plan(
            _fresh_rc(mesh4), _arrivals(), 1, mesh4,
            F.zero_plan(ROUNDS, N_SERVERS),
            decisions_per_step=K, advance_ns=ADVANCE_NS)
        assert RC.decision_digest(seq_none) == \
            RC.decision_digest(seq_zero)
        # the underlying cluster state converges identically too
        for a, b in zip(jax.tree.leaves(rc.cluster),
                        jax.tree.leaves(rc2.cluster)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("tracker_kind", [
        "orig", pytest.param("borrowing", marks=pytest.mark.slow)])
    def test_zero_plan_identity_both_trackers(self, mesh4,
                                              tracker_kind):
        _, seq_none = RC.run_with_plan(
            _fresh_rc(mesh4, tracker_kind), _arrivals(), 1, mesh4,
            None, decisions_per_step=K, advance_ns=ADVANCE_NS)
        _, seq_zero = RC.run_with_plan(
            _fresh_rc(mesh4, tracker_kind), _arrivals(), 1, mesh4,
            F.zero_plan(ROUNDS, N_SERVERS),
            decisions_per_step=K, advance_ns=ADVANCE_NS)
        assert RC.decision_digest(seq_none) == \
            RC.decision_digest(seq_zero)


class TestDegradedMode:
    def test_one_server_down_window(self, mesh4):
        plan = F.single_outage_plan(ROUNDS, N_SERVERS, server=2,
                                    down_from=2, down_until=4)
        arrivals = _arrivals()
        rc, seq = RC.run_with_plan(
            _fresh_rc(mesh4), arrivals, 1, mesh4, plan,
            decisions_per_step=K, advance_ns=ADVANCE_NS)

        # (a) the down server committed nothing during the outage ...
        for t in (2, 3):
            assert (np.asarray(seq[t].type)[2] == 2).all(), \
                "down server handed out decisions"
        # ... and resumed serving after the restart
        assert (np.asarray(seq[4].type)[2] == 0).sum() == N_CLIENTS

        # (b) surviving servers' per-client reservation conformance
        # stays within contract over their live windows
        rows = RC.cluster_conformance(seq, arrivals, plan, QOS,
                                      ADVANCE_NS)
        misses = [r for r in rows if not r["resv_met"]]
        assert not misses, misses

        # (c) fault metric rows match the injected plan EXACTLY
        totals = RC.metrics_totals(rc)
        ev = F.plan_events(plan)
        assert totals["server_dropouts"] == ev["server_dropouts"]
        assert totals["tracker_resyncs"] == ev["tracker_resyncs"]
        assert totals["faults_injected"] == ev["faults_injected"]
        # decision accounting: every client served on every live
        # (server, round)
        live_rounds = int(plan.up.sum())
        assert totals["decisions_total"] == live_rounds * N_CLIENTS

    def test_every_injected_fault_is_visible(self, mesh4):
        plan = F.zero_plan(ROUNDS, N_SERVERS)
        plan.delay_counters[1, 0] = True
        plan.dup_completions[2, 1] = True
        plan.skew_ns[3, 3] = 5_000_000
        plan.up[4, 1] = False            # dropout + restart
        rc, seq = RC.run_with_plan(
            _fresh_rc(mesh4), _arrivals(), 1, mesh4, plan,
            decisions_per_step=K, advance_ns=ADVANCE_NS)
        totals = RC.metrics_totals(rc)
        ev = F.plan_events(plan)
        assert ev["faults_injected"] == 5   # delay+dup+skew+drop+resync
        assert totals["faults_injected"] == ev["faults_injected"]
        assert totals["server_dropouts"] == 1
        assert totals["tracker_resyncs"] == 1

    def test_dup_completions_inflate_counters_monotonically(self, mesh4):
        plan = F.zero_plan(ROUNDS, N_SERVERS)
        plan.dup_completions[1:4, 0] = True
        rc, seq = RC.run_with_plan(
            _fresh_rc(mesh4), _arrivals(), 1, mesh4, plan,
            decisions_per_step=K, advance_ns=ADVANCE_NS)
        served = sum(int((np.asarray(d.type)[0] == 0).sum())
                     for d in seq)
        dup_extra = sum(int((np.asarray(d.type)[0] == 0).sum())
                        for t, d in enumerate(seq)
                        if plan.dup_completions[t, 0])
        counted = int(np.asarray(
            rc.cluster.tracker.completed_delta)[0].sum())
        # double-counted completions show up in the counters (and the
        # protocol stays monotone -- the run completed)
        assert counted == served + dup_extra


# ----------------------------------------------------------------------
# guarded epoch wrapper: the three engines, identity + fallback
# ----------------------------------------------------------------------

def _mid_rate_state():
    infos = {c: ClientInfo(100, 10 + (c % 4), 0) for c in range(12)}
    return deep_state(infos, depth=6)


def _low_rate_state():
    """Per-serve tag advance ~1e9 ns: one tag32 batch of serves exits
    the +-2^31 window (the fallback shape, as in tests/test_radix)."""
    infos = {c: ClientInfo(2, 1 + (c % 3), 0) for c in range(12)}
    return deep_state(infos, depth=6)


class TestGuardedEpoch:
    @pytest.mark.slow
    def test_prefix_identity(self):
        now = jnp.int64(4 * S)
        ep = scan_prefix_epoch(_mid_rate_state(), now, 4, 8,
                               anticipation_ns=0)
        ge = run_epoch_guarded(_mid_rate_state(), now,
                               engine="prefix", m=4, k=8)
        assert ge.count == int(np.asarray(ep.count).sum())
        assert ge.rebase_fallbacks == 0 and ge.serial_fallbacks == 0
        for f in ("count", "slot", "phase", "cost", "lb"):
            assert np.array_equal(np.asarray(getattr(ep, f)),
                                  np.asarray(getattr(ge.results[0],
                                                     f))), f
        assert_states_equal(ep.state, ge.state)

    @pytest.mark.slow
    def test_chain_identity(self):
        now = jnp.int64(4 * S)
        ep = scan_chain_epoch(_mid_rate_state(), now, 3, 8,
                              chain_depth=4, anticipation_ns=0)
        ge = run_epoch_guarded(_mid_rate_state(), now, engine="chain",
                               m=3, k=8, chain_depth=4)
        assert ge.count == int(np.asarray(ep.count).sum())
        for f in ("count", "unit_count", "slot", "cls", "length"):
            assert np.array_equal(np.asarray(getattr(ep, f)),
                                  np.asarray(getattr(ge.results[0],
                                                     f))), f
        assert_states_equal(ep.state, ge.state)

    def test_calendar_identity(self):
        now = jnp.int64(4 * S)
        ep = scan_calendar_epoch(_mid_rate_state(), now, 2, steps=8,
                                 anticipation_ns=0)
        ge = run_epoch_guarded(_mid_rate_state(), now,
                               engine="calendar", m=2, k=8)
        assert ge.count == int(np.asarray(ep.count).sum())
        assert np.array_equal(np.asarray(ep.served),
                              np.asarray(ge.results[0].served))
        assert_states_equal(ep.state, ge.state)

    @pytest.mark.slow
    def test_tag32_trip_resumes_on_int64_exactly(self):
        now = jnp.int64(4 * S)
        e64 = scan_prefix_epoch(_low_rate_state(), now, 4, 8,
                                anticipation_ns=0, tag_width=64)
        e32 = scan_prefix_epoch(_low_rate_state(), now, 4, 8,
                                anticipation_ns=0, tag_width=32)
        assert not bool(np.asarray(e32.guards_ok).all()), \
            "shape was supposed to trip the tag32 window"
        ge = run_epoch_guarded(_low_rate_state(), now,
                               engine="prefix", m=4, k=8,
                               tag_width=32)
        assert ge.rebase_fallbacks == 1
        assert ge.count == int(np.asarray(e64.count).sum())
        assert_states_equal(e64.state, ge.state)


class TestRetryBackoff:
    def test_recovers_after_transients(self):
        calls, sleeps = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert retry_with_backoff(flaky, sleep=sleeps.append) == "ok"
        assert len(calls) == 3
        # bounded exponential: base, base*factor
        assert sleeps == [0.05, 0.1]

    def test_exhaustion_reraises(self):
        def dead():
            raise OSError("hard down")

        with pytest.raises(OSError, match="hard down"):
            retry_with_backoff(dead, retries=2, sleep=lambda s: None)

    def test_plain_runtime_error_not_retried(self):
        # a generic host-side RuntimeError is a caller bug, not a
        # transient device failure -- it must surface immediately
        calls = []

        def bug():
            calls.append(1)
            raise RuntimeError("host bug")

        with pytest.raises(RuntimeError):
            retry_with_backoff(bug, sleep=lambda s: None)
        assert len(calls) == 1

    def test_non_recoverable_raises_immediately(self):
        calls = []

        def bug():
            calls.append(1)
            raise ValueError("caller bug")

        with pytest.raises(ValueError):
            retry_with_backoff(bug, sleep=lambda s: None)
        assert len(calls) == 1

    @staticmethod
    def _always_flaky(fails):
        state = {"n": 0}

        def fn():
            state["n"] += 1
            if state["n"] <= fails:
                raise OSError("transient")
            return "ok"

        return fn

    def test_seeded_jitter_is_deterministic(self):
        """Same seed -> same sleep schedule (replayable under the
        supervisor's determinism discipline); a different seed moves
        it; every jittered delay stays in [0.5, 1.5) x the unjittered
        rung."""
        a, b, c = [], [], []
        retry_with_backoff(self._always_flaky(3), retries=3,
                           sleep=a.append, jitter_seed=7)
        retry_with_backoff(self._always_flaky(3), retries=3,
                           sleep=b.append, jitter_seed=7)
        retry_with_backoff(self._always_flaky(3), retries=3,
                           sleep=c.append, jitter_seed=8)
        assert a == b and len(a) == 3
        assert a != c
        for slept, rung in zip(a, [0.05, 0.1, 0.2]):
            assert 0.5 * rung <= slept < 1.5 * rung

    def test_unseeded_schedule_is_the_exact_ladder(self):
        # regression: callers without a seed keep the historical
        # deterministic rungs bit-for-bit
        sleeps = []
        retry_with_backoff(self._always_flaky(3), retries=3,
                           sleep=sleeps.append)
        assert sleeps == [0.05, 0.1, 0.2]

    def test_deadline_reraises_with_retries_left(self):
        """Wall-clock budget exhausted -> the transient surfaces even
        though the retry count would allow another attempt."""
        now = {"t": 0.0}

        def fn():
            now["t"] += 0.9         # each attempt burns 0.9s
            raise OSError("transient")

        sleeps = []
        with pytest.raises(OSError, match="transient"):
            retry_with_backoff(fn, retries=10, base_s=0.5,
                               deadline_s=2.0, sleep=sleeps.append,
                               clock=lambda: now["t"])
        # attempts at t=0.9, 1.8; the third would start past the
        # 2.0s deadline, so only two sleeps ever happened
        assert len(sleeps) == 2

    def test_deadline_truncates_final_sleep(self):
        now = {"t": 0.0}

        def fn():
            now["t"] += 0.9
            raise OSError("transient")

        sleeps = []
        with pytest.raises(OSError):
            retry_with_backoff(fn, retries=10, base_s=0.5,
                               deadline_s=1.0, sleep=sleeps.append,
                               clock=lambda: now["t"])
        # 0.9s of the 1.0s budget is gone at the first retry: the
        # 0.5s rung is truncated to the 0.1s remaining
        assert len(sleeps) == 1
        assert sleeps[0] == pytest.approx(0.1)


# ----------------------------------------------------------------------
# queue-level guarded commit
# ----------------------------------------------------------------------

def _queue(**kw):
    infos = {c: ClientInfo(10, 1.0 + c % 3, 0) for c in range(4)}
    return TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                                ring_capacity=8, **kw)


class TestQueueGuardedCommit:
    def test_invalid_cost_commits_nothing(self):
        q = _queue()
        for bad in (0, -3, "nan"):
            assert q.add_request(("r", bad), 0, ReqParams(1, 1),
                                 time_ns=S, cost=bad) == errno.EINVAL
        assert q.invalid_cost_rejects == 3
        # nothing was committed: no client record, no queued request
        assert q.client_count() == 0 and q.request_count() == 0
        assert q.pull_request(2 * S).is_none()
        # the same client then adds normally
        assert q.add_request(("r", 1), 0, ReqParams(1, 1),
                             time_ns=S, cost=1) == 0
        assert q.pull_request(2 * S).is_retn()

    def test_transient_launch_failure_retried(self):
        # a pending add makes pull_request take the fused
        # ingest+run launch -- wrap that one
        sleeps = []
        q = _queue(retry_sleep=sleeps.append)
        real = q._jit_ingest_run
        fails = {"n": 2}

        def flaky(steps, advance):
            fn = real(steps, advance)

            def wrapped(*a):
                if fails["n"] > 0:
                    fails["n"] -= 1
                    raise OSError("tunnel wedged")
                return fn(*a)
            return wrapped

        q._jit_ingest_run = flaky
        q.add_request(("r", 0), 0, ReqParams(1, 1), time_ns=S, cost=1)
        pr = q.pull_request(2 * S)
        assert pr.is_retn()
        assert q.guard_retries == 2
        assert len(sleeps) == 2

    def test_launch_failure_exhaustion_raises_with_state_intact(self):
        q = _queue(device_retries=2, retry_sleep=lambda s: None)
        q.add_request(("r", 0), 0, ReqParams(1, 1), time_ns=S, cost=1)

        def dead(steps, advance):
            def wrapped(*a):
                raise OSError("hard down")
            return wrapped

        real = q._jit_ingest_run
        q._jit_ingest_run = dead
        with pytest.raises(OSError, match="hard down"):
            q.pull_request(2 * S)
        assert q.guard_retries == 2
        # state never half-committed: restoring the device path serves
        # the request that was still queued (the op batch survived the
        # failed launches)
        q._jit_ingest_run = real
        assert q.pull_request(2 * S).is_retn()


# ----------------------------------------------------------------------
# registry scrape endpoint
# ----------------------------------------------------------------------

class TestScrapeEndpoint:
    def test_serves_prometheus_and_json(self):
        reg = MetricsRegistry()
        reg.counter("robust_test_total", "a counter").inc(3)
        reg.gauge("robust_test_depth").set_function(lambda: 7)
        with start_http_server(reg, port=0) as srv:
            text = urllib.request.urlopen(srv.url, timeout=10) \
                .read().decode()
            assert "# TYPE robust_test_total counter" in text
            assert "robust_test_total 3" in text
            assert "robust_test_depth 7" in text
            js = json.loads(urllib.request.urlopen(
                srv.url + ".json", timeout=10).read().decode())
            assert js["robust_test_total"][0]["value"] == 3
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=10)

    def test_dmc_sim_wiring(self, tmp_path, capsys):
        conf = tmp_path / "tiny.conf"
        conf.write_text("""
[global]
server_groups = 1
client_groups = 1
[client.0]
client_count = 2
client_wait = 0
client_total_ops = 40
client_server_select_range = 1
client_iops_goal = 100
client_outstanding_ops = 4
client_reservation = 0.0
client_limit = 0.0
client_weight = 1.0
[server.0]
server_count = 1
server_iops = 200
server_threads = 1
""")
        from dmclock_tpu.sim import dmc_sim
        rc = dmc_sim.main(["-c", str(conf), "--metrics-port", "0"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "# metrics: serving http://127.0.0.1:" in out
