"""Observability-layer tests.

The load-bearing contract is the guard test: enabling the on-device
metrics vector must not perturb the decision stream -- `with_metrics`
is a STATIC flag that only adds reductions over arrays the kernels
already materialize, so decisions and final state are bit-identical
with it on or off.  The rest pins the host registry (Prometheus
exposition + JSON snapshot), the ProfileCombiner merge semantics
(reference profile.h:100-120), the bounded JSONL decision trace, and
the sim's per-client QoS conformance table agreeing with the trace.
"""

import json
import math
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo, NS_PER_SEC
from dmclock_tpu.engine import kernels
from dmclock_tpu.engine.fastpath import (scan_calendar_epoch,
                                         scan_chain_epoch,
                                         scan_prefix_epoch)
from dmclock_tpu.obs import (DecisionTrace, MetricsRegistry,
                             validate_trace_file)
from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.sim import ClientGroup, ServerGroup, SimConfig
from dmclock_tpu.sim.dmc_sim import run_sim
from dmclock_tpu.utils.profile import ProfileCombiner, ProfileTimer

from engine_helpers import assert_states_equal, build_state, deep_state

S = NS_PER_SEC

INFOS = {
    0: ClientInfo(10.0, 2.0, 50.0),
    1: ClientInfo(5.0, 1.0, 40.0),
    2: ClientInfo(0.0, 3.0, 0.0),
}


def _mixed_state(depth=6):
    return deep_state(INFOS, depth)


# ----------------------------------------------------------------------
# guard: metrics on/off bit-identity
# ----------------------------------------------------------------------

class TestMetricsBitIdentity:
    def test_engine_run_decisions_identical(self):
        steps = 24
        st_off, now_off, dec_off = kernels.engine_run(
            _mixed_state(), jnp.int64(1 * S), steps,
            allow_limit_break=False, anticipation_ns=0)
        st_on, now_on, dec_on, met = kernels.engine_run(
            _mixed_state(), jnp.int64(1 * S), steps,
            allow_limit_break=False, anticipation_ns=0,
            with_metrics=True)
        for name, a, b in zip(dec_off._fields, dec_off, dec_on):
            assert bool(jnp.array_equal(a, b)), \
                f"decision field {name} diverged with metrics on"
        assert_states_equal(st_off, st_on)
        assert int(now_off) == int(now_on)
        # and the vector itself is consistent with the stream
        d = jax.device_get(dec_on)
        m = obsdev.metrics_dict(met)
        served = int((d.type == kernels.RETURNING).sum())
        assert m["decisions_total"] == served
        assert m["decisions_reservation"] + m["decisions_priority"] \
            == served
        assert m["decisions_reservation"] == \
            int(((d.type == kernels.RETURNING) & (d.phase == 0)).sum())

    def test_prefix_epoch_identical(self):
        now = jnp.int64(1 * S)
        ep_off = scan_prefix_epoch(_mixed_state(), now, 3, 4,
                                   anticipation_ns=0)
        ep_on = scan_prefix_epoch(_mixed_state(), now, 3, 4,
                                  anticipation_ns=0, with_metrics=True)
        for f in ("count", "guards_ok", "slot", "phase", "cost", "lb"):
            assert bool(jnp.array_equal(getattr(ep_off, f),
                                        getattr(ep_on, f))), \
                f"epoch field {f} diverged with metrics on"
        assert_states_equal(ep_off.state, ep_on.state)
        m = obsdev.metrics_dict(ep_on.metrics)
        total = int(jax.device_get(ep_on.count).sum())
        assert m["decisions_total"] == total
        assert m["decisions_reservation"] + m["decisions_priority"] \
            == total
        # metrics-off epochs still carry the field, as zeros
        assert obsdev.metrics_dict(ep_off.metrics) == \
            {k: 0 for k in obsdev.METRIC_NAMES}

    def test_chain_epoch_identical(self):
        now = jnp.int64(1 * S)
        kw = dict(chain_depth=3, anticipation_ns=0, use_pallas=False)
        ep_off = scan_chain_epoch(_mixed_state(), now, 2, 4, **kw)
        ep_on = scan_chain_epoch(_mixed_state(), now, 2, 4,
                                 with_metrics=True, **kw)
        for f in ("count", "unit_count", "guards_ok", "slot", "cls",
                  "length"):
            assert bool(jnp.array_equal(getattr(ep_off, f),
                                        getattr(ep_on, f))), \
                f"chain epoch field {f} diverged with metrics on"
        assert_states_equal(ep_off.state, ep_on.state)
        m = obsdev.metrics_dict(ep_on.metrics)
        assert m["decisions_total"] == \
            int(jax.device_get(ep_on.count).sum())

    def test_calendar_epoch_identical(self):
        now = jnp.int64(1 * S)
        kw = dict(steps=4, anticipation_ns=0, use_pallas=False)
        ep_off = scan_calendar_epoch(_mixed_state(), now, 2, **kw)
        ep_on = scan_calendar_epoch(_mixed_state(), now, 2,
                                    with_metrics=True, **kw)
        for f in ("count", "resv_count", "progress_ok", "served"):
            assert bool(jnp.array_equal(getattr(ep_off, f),
                                        getattr(ep_on, f))), \
                f"calendar epoch field {f} diverged with metrics on"
        assert_states_equal(ep_off.state, ep_on.state)
        m = obsdev.metrics_dict(ep_on.metrics)
        total = int(jax.device_get(ep_on.count).sum())
        assert m["decisions_total"] == total
        assert m["decisions_reservation"] == \
            int(jax.device_get(ep_on.resv_count).sum())

    def test_ring_hwm_bounded_by_depth(self):
        ep = scan_prefix_epoch(_mixed_state(depth=6), jnp.int64(1 * S),
                               2, 4, anticipation_ns=0,
                               with_metrics=True)
        m = obsdev.metrics_dict(ep.metrics)
        assert 0 < m["ring_occupancy_hwm"] <= 6


# ----------------------------------------------------------------------
# obs.device vector algebra
# ----------------------------------------------------------------------

class TestDeviceVector:
    def test_combine_adds_counters_maxes_hwm(self):
        a = obsdev.metrics_delta(decisions=5, resv=2, prop=3,
                                 ring_hwm=7)
        b = obsdev.metrics_delta(decisions=4, resv=4, ring_hwm=3,
                                 ingest_drops=11)
        m = obsdev.metrics_dict(obsdev.metrics_combine(a, b))
        assert m["decisions_total"] == 9
        assert m["decisions_reservation"] == 6
        assert m["decisions_priority"] == 3
        assert m["ring_occupancy_hwm"] == 7      # max, not 10
        assert m["ingest_drops"] == 11

    def test_combine_commutative(self):
        a = obsdev.metrics_delta(decisions=5, ring_hwm=2, stalls=1)
        b = obsdev.metrics_delta(decisions=1, ring_hwm=9,
                                 guard_trips=2)
        ab = obsdev.metrics_combine(a, b)
        ba = obsdev.metrics_combine(b, a)
        assert bool(jnp.array_equal(ab, ba))

    def test_admission_clamp_counts_drops(self):
        counts = jnp.asarray([5, 3, 0, 9], dtype=jnp.int32)
        headroom = jnp.asarray([2, 3, 4, 0], dtype=jnp.int32)
        clamped, dropped = obsdev.admission_clamp(counts, headroom)
        assert jax.device_get(clamped).tolist() == [2, 3, 0, 0]
        assert int(dropped) == 3 + 9

    def test_np_combine_mirrors_device_combine(self):
        a = obsdev.metrics_delta(decisions=5, resv=2, prop=3,
                                 ring_hwm=7, stalls=1)
        b = obsdev.metrics_delta(decisions=4, resv=4, ring_hwm=3,
                                 guard_trips=2, ingest_drops=11)
        dev = np.asarray(jax.device_get(obsdev.metrics_combine(a, b)))
        host = obsdev.metrics_combine_np(np.asarray(jax.device_get(a)),
                                         np.asarray(jax.device_get(b)))
        assert np.array_equal(dev, host)

    def test_publish_into_registry(self):
        reg = MetricsRegistry()
        vec = obsdev.metrics_delta(decisions=8, resv=3, prop=5,
                                   ring_hwm=4)
        obsdev.publish(reg, vec, prefix="eng")
        snap = reg.snapshot()
        assert snap["eng_decisions_total"][0]["value"] == 8
        assert snap["eng_ring_occupancy_hwm"][0]["value"] == 4


# ----------------------------------------------------------------------
# ProfileCombiner merge semantics (reference profile.h:100-120)
# ----------------------------------------------------------------------

class TestProfileCombiner:
    def test_multi_server_merge_matches_single_timer(self):
        rng = random.Random(7)
        durations = [[rng.randrange(100, 50_000) for _ in range(40)]
                     for _ in range(4)]       # 4 simulated servers
        per_server = []
        for ds in durations:
            t = ProfileTimer()
            for d in ds:
                t._accumulate(d)
            per_server.append(t)
        single = ProfileTimer()
        for ds in durations:
            for d in ds:
                single._accumulate(d)
        comb = ProfileCombiner()
        for t in per_server:
            comb.combine(t)
        assert comb.count == single.count == 160
        assert comb.sum_ns == single.sum_ns
        assert comb.low_ns == single.low_ns == min(map(min, durations))
        assert comb.high_ns == single.high_ns == max(map(max, durations))
        assert math.isclose(comb.mean_ns(), single.mean_ns())
        assert math.isclose(comb.std_dev_ns(), single.std_dev_ns())
        assert comb.std_dev_ns() > 0

    def test_empty_timer_is_identity(self):
        t = ProfileTimer()
        t._accumulate(500)
        comb = ProfileCombiner()
        comb.combine(ProfileTimer())      # no-op
        comb.combine(t)
        comb.combine(ProfileTimer())      # no-op
        assert (comb.count, comb.sum_ns, comb.low_ns, comb.high_ns) \
            == (1, 500, 500, 500)

    def test_double_start_restarts_cleanly_and_counts(self):
        # regression: start() on a running timer used to assert (and
        # under PYTHONOPTIMIZE silently discard the in-flight
        # interval); now it restarts cleanly and counts a reentry
        t = ProfileTimer()
        t.start()
        t.start()                 # reentrant start: abandon + restart
        t.stop()
        assert t.reentries == 1
        assert t.count == 1       # exactly one interval accumulated
        assert t.sum_ns >= 0
        t.start()
        t.stop()
        assert t.reentries == 1 and t.count == 2
        # a stop without a start still asserts (a stop cannot invent
        # an interval)
        with pytest.raises(AssertionError):
            ProfileTimer().stop()

    def test_reentries_visible_at_the_drain(self):
        # the abandoned interval deflates count/sum, so the stat must
        # surface in the registry drain or the discard stays silent
        reg = MetricsRegistry()
        t1, t2 = ProfileTimer(), ProfileTimer()
        t1.start()
        t1.start()
        t1.stop()
        reg.timer("x_ns", source=t1)
        reg.timer("x_ns", source=t2)
        tm = reg.timer("x_ns")
        assert tm.value_obj()["reentries"] == 1
        assert ("_reentries", {}, 1) in tm.sample_rows()
        assert "x_ns_reentries 1" in reg.prometheus()


# ----------------------------------------------------------------------
# host registry
# ----------------------------------------------------------------------

class TestRegistry:
    def test_get_or_create_is_idempotent(self):
        reg = MetricsRegistry()
        c1 = reg.counter("x_total", "help")
        c1.inc(3)
        assert reg.counter("x_total").value == 3
        # distinct labels => distinct instance
        assert reg.counter("x_total", labels={"s": "1"}).value == 0
        with pytest.raises(AssertionError):
            reg.gauge("x_total")      # kind mismatch

    def test_prometheus_exposition(self):
        reg = MetricsRegistry()
        reg.counter("dec_total", "decisions").inc(5)
        reg.gauge("depth", "ring depth", labels={"server": "0"}).set(17)
        h = reg.histogram("lat_ns", buckets=(10, 100))
        h.observe(5)
        h.observe(50)
        h.observe(1e9)
        text = reg.prometheus()
        assert "# TYPE dec_total counter" in text
        assert "dec_total 5" in text
        assert 'depth{server="0"} 17' in text
        assert 'lat_ns_bucket{le="10"} 1' in text
        assert 'lat_ns_bucket{le="100"} 2' in text
        assert 'lat_ns_bucket{le="+Inf"} 3' in text
        assert "lat_ns_count 3" in text

    def test_prometheus_families_contiguous(self):
        # label variants registered interleaved with other metrics
        # must still drain as one contiguous family (format 0.0.4)
        reg = MetricsRegistry()
        reg.gauge("depth", "d", labels={"server": "0"}).set(1)
        reg.counter("other_total").inc()
        reg.gauge("depth", "d", labels={"server": "1"}).set(2)
        lines = reg.prometheus().splitlines()
        idx = [i for i, l in enumerate(lines)
               if l.startswith("depth{")]
        assert idx == [idx[0], idx[0] + 1], f"family split: {lines}"
        assert lines.count("# TYPE depth gauge") == 1

    def test_timer_metric_merges_sources(self):
        reg = MetricsRegistry()
        t1, t2 = ProfileTimer(), ProfileTimer()
        t1._accumulate(100)
        t2._accumulate(300)
        reg.timer("op_ns", source=t1)
        reg.timer("op_ns", source=t2)
        v = reg.snapshot()["op_ns"][0]["value"]
        assert v["count"] == 2
        assert v["sum_ns"] == 400
        assert v["min_ns"] == 100 and v["max_ns"] == 300
        assert v["mean_ns"] == 200.0

    def test_snapshot_json_round_trips(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc()
        assert json.loads(reg.snapshot_json())["a_total"][0]["value"] \
            == 1

    def test_callback_gauge_reads_lazily(self):
        reg = MetricsRegistry()
        box = {"v": 1}
        reg.gauge("live").set_function(lambda: box["v"])
        box["v"] = 42
        assert reg.snapshot()["live"][0]["value"] == 42


# ----------------------------------------------------------------------
# decision trace + sim conformance
# ----------------------------------------------------------------------

def _small_cfg(total_ops=60):
    return SimConfig(
        client_groups=2, server_groups=1,
        cli_group=[
            ClientGroup(client_count=2, client_total_ops=total_ops,
                        client_iops_goal=80.0, client_reservation=25.0,
                        client_limit=100.0, client_weight=1.0,
                        client_outstanding_ops=16,
                        client_server_select_range=1),
            ClientGroup(client_count=1, client_total_ops=total_ops,
                        client_iops_goal=80.0, client_reservation=0.0,
                        client_limit=0.0, client_weight=2.0,
                        client_outstanding_ops=16,
                        client_server_select_range=1),
        ],
        srv_group=[ServerGroup(server_count=1, server_iops=200.0,
                               server_threads=2)])


class TestDecisionTrace:
    def test_bounded_writer_and_validator(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        with DecisionTrace(p, limit=3) as tr:
            for i in range(5):
                tr.record(1000 + i, 0, i % 2, i % 2, 1,
                          tag=(10, 20, 30) if i % 2 else None)
        assert tr.rows_written == 3 and tr.rows_dropped == 2
        stats = validate_trace_file(p)
        assert stats["rows"] == 3
        assert stats["per_client"] == {0: 2, 1: 1}
        assert stats["per_phase"]["reservation"] == 2

    def test_validator_rejects_bad_rows(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text('{"t": 1, "server": 0, "client": 0, '
                     '"phase": "warp", "cost": 1, "tag": null}\n')
        with pytest.raises(ValueError, match="bad phase"):
            validate_trace_file(str(p))
        p.write_text('{"t": 1}\n')
        with pytest.raises(ValueError, match="fields"):
            validate_trace_file(str(p))

    def test_sim_trace_matches_conformance_table(self, tmp_path):
        p = str(tmp_path / "sim.jsonl")
        trace = DecisionTrace(p)
        sim = run_sim(_small_cfg(), seed=99, decision_trace=trace)
        trace.close()
        stats = validate_trace_file(p)
        rows = sim.report().conformance()
        # every decision traced exactly once, per client
        assert stats["per_client"] == \
            {r["client"]: r["ops"] for r in rows}
        assert stats["rows"] == sum(r["ops"] for r in rows) == 3 * 60
        per_phase = {r["client"]: (r["reservation_ops"],
                                   r["priority_ops"]) for r in rows}
        assert stats["per_phase"]["reservation"] == \
            sum(v[0] for v in per_phase.values())
        assert stats["per_phase"]["priority"] == \
            sum(v[1] for v in per_phase.values())
        # the dmclock pull path materializes tags: every row carries one
        with open(p) as fh:
            first = json.loads(fh.readline())
        assert first["tag"] is not None and len(first["tag"]) == 3

    def test_sim_registry_agrees_with_report(self):
        sim = run_sim(_small_cfg(), seed=5)
        rep = sim.report()
        snap = sim.registry.snapshot()
        assert snap["sim_ops_completed_total"][0]["value"] \
            == rep.total_ops == 3 * 60
        assert snap["sim_reservation_ops_total"][0]["value"] \
            == rep.total_reservation_ops
        assert snap["sim_priority_ops_total"][0]["value"] \
            == rep.total_priority_ops
        # per-server scheduling counters came in via register_metrics
        assert "dmclock_sched_reservation_total" in snap
        text = sim.registry.prometheus()
        assert "sim_ops_completed_total 180" in text

    def test_conformance_verdicts(self):
        sim = run_sim(_small_cfg(), seed=13)
        rows = sim.report().conformance()
        assert len(rows) == 3
        for r in rows:
            # closed-loop demand-aware floor: clients that asked got
            # their reservation within tolerance
            assert r["resv_met"], f"client {r['client']} missed resv"
        table = sim.report().format_conformance()
        assert "per-client QoS conformance" in table
        assert f"total ops {sum(r['ops'] for r in rows)}" in table
