"""Cluster multi-tenant realism: per-client costs, mid-run client
creation, and (gated) large-scale host-composition parity.

Extends the round-synchronous cluster parity gate
(``test_parallel.py::test_cluster_step_matches_independent_host_sims``)
with the workload dimensions a real multi-tenant deployment has:
heterogeneous per-request costs within a round, clients appearing
mid-run (OP_CREATE through the sharded ingest), and -- behind
``DMCLOCK_FULLSCALE=1`` (run by ``scripts/run_fullscale.py`` in CI) --
the same exact per-decision parity at 8 servers x 1000 clients x 10
rounds for BOTH tracker policies.
"""

import functools
import os
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import (ClientInfo, Phase, PullPriorityQueue,
                              ReqParams)
from dmclock_tpu.core.scheduler import NextReqType
from dmclock_tpu.core.timebase import rate_to_inv_ns
from dmclock_tpu.core.tracker import (BorrowingTracker, OrigTracker,
                                      ServiceTracker)
from dmclock_tpu.parallel import cluster as CL


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return CL.make_mesh(8)


def run_parity(mesh, n_servers, n_clients, rounds, k, max_arr,
               tracker_kind, seed, cost_of=None, create_at=None):
    """Device cluster vs host composition (S oracle queues + C host
    trackers), exact per-decision.  ``cost_of(c)`` gives client c's
    per-request cost; ``create_at`` maps round -> list of client slots
    created right before that round's arrivals (initial population is
    every slot not created later)."""
    infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0)
             for c in range(n_clients)]
    cost_of = cost_of or (lambda c: 1)
    costs = jnp.asarray([cost_of(c) for c in range(n_clients)],
                        dtype=jnp.int64)
    created_later = set()
    create_at = create_at or {}
    for slots in create_at.values():
        created_later.update(slots)

    rinv = jnp.asarray([i.reservation_inv_ns for i in infos], jnp.int64)
    winv = jnp.asarray([i.weight_inv_ns for i in infos], jnp.int64)
    linv = jnp.asarray([i.limit_inv_ns for i in infos], jnp.int64)
    initial = jnp.asarray([c not in created_later
                           for c in range(n_clients)])

    cl = CL.init_cluster(n_servers, n_clients,
                         tracker_kind=tracker_kind)
    cl = CL.install_clients(cl, rinv, winv, linv, active_mask=initial)
    cl = CL.shard_cluster(cl, mesh)
    step = jax.jit(functools.partial(
        CL.cluster_step, mesh=mesh, cost=costs, decisions_per_step=k,
        max_arrivals=max_arr))

    queues = [PullPriorityQueue(lambda c, i=s: infos[c],
                                delayed_tag_calc=True,
                                run_gc_thread=False)
              for s in range(n_servers)]
    host_cls = {"orig": OrigTracker,
                "borrowing": BorrowingTracker}[tracker_kind]
    trackers = [ServiceTracker(tracker_cls=host_cls, run_gc_thread=False)
                for _ in range(n_clients)]
    host_now = [0] * n_servers

    active = np.asarray(initial).copy()
    rng = random.Random(seed)
    for rnd in range(rounds + 1):
        if rnd in create_at:
            new = np.zeros(n_clients, dtype=bool)
            new[create_at[rnd]] = True
            cl = CL.create_clients(cl, jnp.asarray(new), rinv, winv,
                                   linv, mesh)
            active |= new
        if rnd == 0:
            # first contacts in slot order fix the host tie-break rank
            arrivals = np.tile(active.astype(np.int32),
                               (n_servers, 1))
        else:
            arrivals = np.asarray(
                [[rng.randint(0, max_arr) if active[c] else 0
                  for c in range(n_clients)]
                 for _ in range(n_servers)], dtype=np.int32)
            # a just-created population's first contacts also happen in
            # slot order within wave 0 (ingest is wave-major) -- force
            # at least one request so creation order matches the host
            for c in range(n_clients):
                if rnd in create_at and c in create_at[rnd]:
                    arrivals[:, c] = np.maximum(arrivals[:, c], 1)

        cl, decs = step(cl, jnp.asarray(arrivals))
        d_type = np.asarray(decs.type)
        d_slot = np.asarray(decs.slot)
        d_phase = np.asarray(decs.phase)
        d_cost = np.asarray(decs.cost)
        d_when = np.asarray(decs.when)
        d_now = np.asarray(cl.now)

        for s in range(n_servers):
            for wave in range(max_arr):
                for c in range(n_clients):
                    if arrivals[s][c] > wave:
                        rp = trackers[c].get_req_params(s)
                        queues[s].add_request(
                            (rnd, wave, c), c,
                            ReqParams(rp.delta, rp.rho),
                            time_ns=host_now[s], cost=int(costs[c]))
        for s in range(n_servers):
            responses = []
            for i in range(k):
                pr = queues[s].pull_request(host_now[s])
                if pr.type is NextReqType.RETURNING:
                    assert (d_type[s][i], d_slot[s][i], d_phase[s][i],
                            d_cost[s][i]) == \
                        (0, pr.client, int(pr.phase is Phase.PRIORITY),
                         pr.cost), \
                        f"round {rnd} server {s} step {i}"
                    responses.append((pr.client, pr.phase, pr.cost))
                elif pr.type is NextReqType.FUTURE:
                    assert (d_type[s][i], d_when[s][i]) == \
                        (1, pr.when_ready), \
                        f"round {rnd} server {s} step {i} FUTURE"
                    host_now[s] = pr.when_ready
                else:
                    assert d_type[s][i] == 2, \
                        f"round {rnd} server {s} step {i} NONE"
            assert host_now[s] == d_now[s], f"round {rnd} server {s}"
            for client, phase, cost in responses:
                trackers[client].track_resp(s, phase, cost)


def test_per_client_costs_parity(mesh8):
    """Heterogeneous request costs within a round: cost feeds the tag
    recurrence (units = dist + cost) and the completion accounting, so
    parity here pins the whole cost path."""
    run_parity(mesh8, n_servers=8, n_clients=10, rounds=3, k=24,
               max_arr=2, tracker_kind="orig", seed=31,
               cost_of=lambda c: 1 + (c % 3))


@pytest.mark.slow
def test_midrun_client_creation_parity(mesh8):
    """Clients appear mid-run (rounds 1 and 2) via the sharded
    OP_CREATE ingest; the decision streams must still match the host
    composition that admits them at first contact."""
    run_parity(mesh8, n_servers=8, n_clients=12, rounds=4, k=24,
               max_arr=2, tracker_kind="orig", seed=37,
               create_at={1: [8, 9], 2: [10, 11]})


@pytest.mark.slow
def test_midrun_creation_borrowing(mesh8):
    run_parity(mesh8, n_servers=8, n_clients=9, rounds=3, k=20,
               max_arr=2, tracker_kind="borrowing", seed=41,
               create_at={1: [6, 7, 8]},
               cost_of=lambda c: 1 + (c % 2))


def test_metrics_mesh_merge_matches_host(mesh8):
    """Healthy-path in-graph metrics merge (ROADMAP multichip psum
    item): cluster_step(with_metrics=True) psums counter rows and
    pmaxes hwm rows across the mesh; the merged vector must equal the
    host-side metrics_combine_np over the per-shard vectors, and the
    decision stream must be bit-identical with the flag off."""
    from dmclock_tpu.obs import device as obsdev

    n_servers, n_clients, k = 8, 10, 16
    infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0)
             for c in range(n_clients)]
    rinv = jnp.asarray([i.reservation_inv_ns for i in infos],
                       jnp.int64)
    winv = jnp.asarray([i.weight_inv_ns for i in infos], jnp.int64)
    linv = jnp.asarray([i.limit_inv_ns for i in infos], jnp.int64)
    cl = CL.init_cluster(n_servers, n_clients)
    cl = CL.install_clients(cl, rinv, winv, linv)
    cl = CL.shard_cluster(cl, mesh8)
    arrivals = jnp.ones((n_servers, n_clients), jnp.int32)
    step_off = functools.partial(CL.cluster_step, mesh=mesh8, cost=1,
                                 decisions_per_step=k,
                                 advance_ns=10 ** 8)
    step_on = functools.partial(step_off, with_metrics=True)

    jit_off, jit_on = jax.jit(step_off), jax.jit(step_on)
    cl_off, cl_on = cl, cl
    total = np.zeros(obsdev.NUM_METRICS, np.int64)
    for _ in range(3):
        cl_off, d_off = jit_off(cl_off, arrivals)
        cl_on, d_on, shard_met, merged = jit_on(cl_on, arrivals)
        for a, b in zip(jax.tree.leaves(d_off), jax.tree.leaves(d_on)):
            assert bool(jnp.array_equal(a, b)), \
                "decisions diverged with metrics on"
        shard_np = np.asarray(jax.device_get(shard_met))
        assert shard_np.shape == (n_servers, obsdev.NUM_METRICS)
        host = obsdev.metrics_combine_np(
            np.zeros(obsdev.NUM_METRICS, np.int64), *shard_np)
        assert np.array_equal(host, np.asarray(jax.device_get(merged))), \
            "in-graph mesh merge != host-side combine"
        total = obsdev.metrics_combine_np(total, host)
    md = obsdev.metrics_dict(total)
    assert md["decisions_total"] > 0
    assert md["decisions_reservation"] + md["decisions_priority"] == \
        md["decisions_total"]


def test_robust_mesh_merge_matches_host_under_faults(mesh8):
    """ROBUST-path in-graph metrics merge (the remaining ROADMAP
    multichip sub-item): under a seeded NONZERO FaultPlan --
    dropouts, stale counter views, skew, duplicated completions all
    active -- robust_cluster_step(with_merged=True) must return a
    mesh-merged (psum counters / pmax hwm) total of the per-shard
    held-view vectors equal to the host-side metrics_combine_np over
    those shards, at every step, fault rows included."""
    import functools

    from dmclock_tpu.obs import device as obsdev
    from dmclock_tpu.robust import cluster as RC
    from dmclock_tpu.robust import faults as F

    n_servers, n_clients, steps, k = 8, 10, 6, 16
    adv = 10 ** 8
    infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0)
             for c in range(n_clients)]
    cl = CL.init_cluster(n_servers, n_clients)
    cl = CL.install_clients(
        cl,
        jnp.asarray([i.reservation_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.weight_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.limit_inv_ns for i in infos], jnp.int64))
    rc = RC.shard_robust(RC.init_robust(CL.shard_cluster(cl, mesh8)),
                         mesh8)
    plan = F.sample_plan(23, steps, n_servers, p_dropout=0.25,
                         mean_outage_steps=2.0, p_delay=0.3,
                         p_dup=0.2, max_skew_ns=1000)
    assert F.plan_events(plan)["faults_injected"] > 0, \
        "seeded plan must be nonzero for this gate"
    step = jax.jit(functools.partial(
        RC.robust_cluster_step, cost=1, mesh=mesh8,
        decisions_per_step=k, advance_ns=adv, with_merged=True))
    arrivals = jnp.ones((n_servers, n_clients), jnp.int32)
    for t in range(steps):
        rc, _decs, merged = step(rc, arrivals,
                                 fault=F.plan_step(plan, t))
        shard_np = np.asarray(jax.device_get(rc.metrics))
        assert shard_np.shape == (n_servers, obsdev.NUM_METRICS)
        host = obsdev.metrics_combine_np(
            np.zeros(obsdev.NUM_METRICS, np.int64), *shard_np)
        assert np.array_equal(host,
                              np.asarray(jax.device_get(merged))), \
            f"step {t}: in-graph mesh merge != host-side combine"
    # the merged total carries the fault rows too, matching the oracle
    totals = obsdev.metrics_dict(np.asarray(jax.device_get(merged)))
    ev = F.plan_events(plan)
    assert totals["server_dropouts"] == ev["server_dropouts"]
    assert totals["tracker_resyncs"] == ev["tracker_resyncs"]
    assert totals["faults_injected"] == ev["faults_injected"]


def _mesh_gate_cluster(mesh8, n_servers, n_clients, tracker_kind):
    from dmclock_tpu.core.timebase import rate_to_inv_ns

    infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0)
             for c in range(n_clients)]
    cl = CL.init_cluster(n_servers, n_clients,
                         tracker_kind=tracker_kind)
    cl = CL.install_clients(
        cl,
        jnp.asarray([i.reservation_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.weight_inv_ns for i in infos], jnp.int64),
        jnp.asarray([i.limit_inv_ns for i in infos], jnp.int64))
    return CL.shard_cluster(cl, mesh8)


@pytest.mark.parametrize("counter_sync_every,tracker_kind", [
    (1, "orig"),
    pytest.param(1, "borrowing", marks=pytest.mark.slow),
    (3, "orig"),
    pytest.param(2, "borrowing", marks=pytest.mark.slow),
])
def test_mesh_rounds_match_host_loop(mesh8, counter_sync_every,
                                     tracker_kind):
    """The mesh serving plane's cluster digest gate (ISSUE-14): ONE
    fused shard_map launch of E whole rounds with the delta/rho
    counter psum exchanged only on the counter_sync_every grid must
    equal E host-driven robust_cluster_steps -- decision stream,
    final counter views, tracker state, AND metrics (modulo the
    faults_injected row: a held view is an injected fault on the host
    path, a configured cadence on the mesh path).  K=1 compares
    against the zero-fault plan; K>1 against a plan that delays the
    counter piggyback on exactly the non-sync rounds -- the staleness
    knob IS the paper's stale-view tolerance, pinned exactly."""
    from dmclock_tpu.obs import device as obsdev
    from dmclock_tpu.robust import cluster as RC
    from dmclock_tpu.robust import faults as F

    n_servers, n_clients, rounds, k, adv = 8, 10, 6, 16, 10 ** 8
    K = counter_sync_every
    rng = np.random.Generator(np.random.PCG64(7))
    arrivals = rng.integers(
        0, 3, size=(rounds, n_servers, n_clients)).astype(np.int32)

    plan = F.zero_plan(rounds, n_servers)
    plan.delay_counters[:] = (np.arange(rounds) % K != 0)[:, None]
    rc = RC.shard_robust(RC.init_robust(
        _mesh_gate_cluster(mesh8, n_servers, n_clients,
                           tracker_kind)), mesh8)
    rc, decs_seq = RC.run_with_plan(
        rc, arrivals, 1, mesh8, plan=plan, decisions_per_step=k,
        max_arrivals=2, advance_ns=adv)

    out = CL.run_mesh_rounds(
        _mesh_gate_cluster(mesh8, n_servers, n_clients, tracker_kind),
        arrivals, 1, mesh8, decisions_per_step=k, max_arrivals=2,
        advance_ns=adv, counter_sync_every=K, with_merged=True)
    assert RC.decision_digest(CL.mesh_decs_seq(out.decs)) == \
        RC.decision_digest(decs_seq), "decision stream diverged"
    assert np.array_equal(np.asarray(out.view_delta),
                          np.asarray(rc.view_delta)), "held views"
    assert np.array_equal(np.asarray(out.view_rho),
                          np.asarray(rc.view_rho))
    for a, b in zip(jax.tree.leaves(out.cluster.tracker),
                    jax.tree.leaves(rc.cluster.tracker)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "tracker counters diverged"
    host_met = np.asarray(rc.metrics).copy()
    host_met[:, obsdev.MET_FAULTS_INJECTED] = 0
    assert np.array_equal(np.asarray(out.metrics), host_met)
    # the in-graph merged vector == host combine over the shards
    host = obsdev.metrics_combine_np(
        np.zeros(obsdev.NUM_METRICS, np.int64),
        *np.asarray(out.metrics))
    assert np.array_equal(host, np.asarray(out.merged))


@pytest.mark.slow
def test_mesh_rounds_one_launch_per_chunk(mesh8):
    """The perf claim the plane ships under: E rounds = ONE compiled
    program execution, not 3E host round-trips -- pinned by running
    the jitted fused program once and getting E rounds of decisions
    whose totals match the host loop's."""
    from dmclock_tpu.robust import cluster as RC
    from dmclock_tpu.robust import faults as F

    n_servers, n_clients, rounds, k = 8, 10, 5, 16
    rng = np.random.Generator(np.random.PCG64(11))
    arrivals = rng.integers(
        0, 2, size=(rounds, n_servers, n_clients)).astype(np.int32)
    cl = _mesh_gate_cluster(mesh8, n_servers, n_clients, "orig")
    vd, vr = CL.init_mesh_views(n_servers, n_clients)
    from dmclock_tpu.obs import device as obsdev
    met = jnp.zeros((n_servers, obsdev.NUM_METRICS), jnp.int64)
    step = CL.jit_mesh_rounds(mesh8, epochs=rounds,
                              decisions_per_step=k, max_arrivals=2,
                              advance_ns=10 ** 8)
    out = step(cl, jnp.asarray(arrivals), jnp.int64(1), vd, vr, met)
    assert np.asarray(out.decs.type).shape == (n_servers, rounds, k)
    rc = RC.shard_robust(RC.init_robust(
        _mesh_gate_cluster(mesh8, n_servers, n_clients, "orig")),
        mesh8)
    rc, decs_seq = RC.run_with_plan(
        rc, arrivals, 1, mesh8, plan=F.zero_plan(rounds, n_servers),
        decisions_per_step=k, max_arrivals=2, advance_ns=10 ** 8)
    assert RC.decision_digest(CL.mesh_decs_seq(out.decs)) == \
        RC.decision_digest(decs_seq)


@pytest.mark.skipif(os.environ.get("DMCLOCK_FULLSCALE") != "1",
                    reason="large-scale cluster parity is minutes-long; "
                    "run via scripts/run_fullscale.py (CI)")
@pytest.mark.parametrize("tracker_kind", ["orig", "borrowing"])
def test_cluster_parity_fullscale(mesh8, tracker_kind):
    """8 servers x 1000 clients x 10 rounds, exact per-decision parity
    for both tracker policies (VERDICT r2 item 5)."""
    run_parity(mesh8, n_servers=8, n_clients=1000, rounds=10, k=1100,
               max_arr=1, tracker_kind=tracker_kind, seed=53,
               cost_of=lambda c: 1 + (c % 3))


@pytest.mark.parametrize("counter_sync_every,tracker_kind", [
    (1, "orig"),
    (2, "orig"),
    pytest.param(4, "orig", marks=pytest.mark.slow),
    pytest.param(1, "borrowing", marks=pytest.mark.slow),
    pytest.param(2, "borrowing", marks=pytest.mark.slow),
    pytest.param(4, "borrowing", marks=pytest.mark.slow),
])
def test_chaos_mesh_rounds_match_host_loop(mesh8, counter_sync_every,
                                           tracker_kind):
    """The degraded-mode mesh's cluster digest gate (ISSUE-15): ONE
    fused launch of E whole rounds under a SEEDED FaultPlan
    (dropout/restart Markov chains, delayed views, duplicated
    completions, clock skew -- all riding the scan as traced masks)
    must equal E host-driven robust_cluster_steps under the same plan
    with the K staleness grid folded into the delay mask
    (robust.cluster.effective_plan): decisions, held views, tracker
    state, metric vectors -- and the K=1 fault rows equal the
    plan_events oracle."""
    from dmclock_tpu.obs import device as obsdev
    from dmclock_tpu.robust import cluster as RC
    from dmclock_tpu.robust import faults as F

    n_servers, n_clients, rounds, k, adv = 8, 10, 6, 16, 10 ** 8
    K = counter_sync_every
    rng = np.random.Generator(np.random.PCG64(7))
    arrivals = rng.integers(
        0, 3, size=(rounds, n_servers, n_clients)).astype(np.int32)
    plan = F.sample_plan(13, rounds, n_servers, p_dropout=0.3,
                         mean_outage_steps=2.0, p_delay=0.2,
                         p_dup=0.2, max_skew_ns=500)
    assert F.plan_events(plan)["server_dropouts"] > 0, \
        "seed must actually drop a server or the gate is vacuous"

    rc_h = RC.shard_robust(RC.init_robust(
        _mesh_gate_cluster(mesh8, n_servers, n_clients,
                           tracker_kind)), mesh8)
    rc_h, decs_seq = RC.run_with_plan(
        rc_h, arrivals, 1, mesh8, plan=RC.effective_plan(plan, K),
        decisions_per_step=k, max_arrivals=2, advance_ns=adv)

    rc_m = RC.shard_robust(RC.init_robust(
        _mesh_gate_cluster(mesh8, n_servers, n_clients,
                           tracker_kind)), mesh8)
    rc_m, decs = RC.run_mesh_rounds_with_plan(
        rc_m, arrivals, 1, mesh8, plan, decisions_per_step=k,
        max_arrivals=2, advance_ns=adv, counter_sync_every=K)

    assert RC.decision_digest(CL.mesh_decs_seq(decs)) == \
        RC.decision_digest(decs_seq), "chaos decision stream diverged"
    assert np.array_equal(np.asarray(rc_m.view_delta),
                          np.asarray(rc_h.view_delta)), "held views"
    assert np.array_equal(np.asarray(rc_m.view_rho),
                          np.asarray(rc_h.view_rho))
    for a, b in zip(jax.tree.leaves(rc_m.cluster.tracker),
                    jax.tree.leaves(rc_h.cluster.tracker)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "tracker counters diverged under chaos"
    assert np.array_equal(np.asarray(rc_m.metrics),
                          np.asarray(rc_h.metrics)), \
        "fault metric rows diverged"
    if K == 1:
        totals = RC.metrics_totals(rc_m)
        ev = F.plan_events(plan)
        for key in ("server_dropouts", "tracker_resyncs",
                    "faults_injected"):
            assert totals[key] == ev[key], (key, totals[key], ev)
