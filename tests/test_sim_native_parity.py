"""Full-simulation trace parity: C++ native backend vs CPU oracle.

Same gate as ``test_sim_tpu_parity.py`` but for the ctypes-bound native
runtime: identical configs and seeds must produce the exact same
service trace through ``--model dmclock-native`` as through the oracle
``dmclock-delayed`` model (both are DelayedTagCalc over the shared
int64-ns total order)."""

import pytest

from dmclock_tpu.sim import ClientGroup, ServerGroup, SimConfig
from dmclock_tpu.sim.dmc_sim import run_sim

native = pytest.importorskip("dmclock_tpu.native")
if native.load_library() is None:
    pytest.skip("native dmclock library unavailable (no toolchain)",
                allow_module_level=True)


def make_cfg(clients, servers, **kw):
    return SimConfig(client_groups=len(clients),
                     server_groups=len(servers),
                     cli_group=clients, srv_group=servers, **kw)


def assert_traces_equal(cfg, seed=7):
    cpu = run_sim(cfg, model="dmclock-delayed", seed=seed,
                  record_trace=True)
    nat = run_sim(cfg, model="dmclock-native", seed=seed,
                  record_trace=True)
    assert len(cpu.trace) == len(nat.trace) > 0
    for i, (a, b) in enumerate(zip(cpu.trace, nat.trace)):
        assert a == b, f"trace diverges at op {i}: cpu={a} native={b}"
    for cid in cpu.clients:
        ca, cb = cpu.clients[cid].stats, nat.clients[cid].stats
        assert (ca.reservation_ops, ca.priority_ops) == \
            (cb.reservation_ops, cb.priority_ops)


def test_trace_parity_example_shape():
    groups = [
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=0,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=1,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=40.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=60, client_wait_s=2,
                    client_iops_goal=200, client_outstanding_ops=32,
                    client_reservation=0.0, client_limit=50.0,
                    client_weight=2.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40, client_wait_s=0,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_req_cost=3,
                    client_server_select_range=1),
    ]
    servers = [ServerGroup(server_count=1, server_iops=160,
                           server_threads=1)]
    assert_traces_equal(make_cfg(groups, servers,
                                 server_soft_limit=False))


def test_trace_parity_100th_shape():
    groups = [
        ClientGroup(client_count=2, client_total_ops=50,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=20.0, client_limit=60.0,
                    client_weight=1.0, client_server_select_range=1),
        ClientGroup(client_count=1, client_total_ops=40,
                    client_iops_goal=100, client_outstanding_ops=16,
                    client_reservation=10.0, client_limit=0.0,
                    client_weight=2.0, client_req_cost=3,
                    client_server_select_range=1),
    ]
    servers = [ServerGroup(server_count=1, server_iops=120,
                           server_threads=2)]
    assert_traces_equal(make_cfg(groups, servers, server_soft_limit=True))


def test_trace_parity_multi_server():
    groups = [
        ClientGroup(client_count=3, client_total_ops=60,
                    client_iops_goal=120, client_outstanding_ops=8,
                    client_reservation=15.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=2),
    ]
    servers = [ServerGroup(server_count=2, server_iops=80,
                           server_threads=1)]
    assert_traces_equal(make_cfg(groups, servers,
                                 server_soft_limit=False))


def test_full_example_conf_native_vs_oracle():
    """The ACTUAL acceptance config, full scale, native vs oracle
    (VERDICT round-1 item 4 demanded real-config coverage)."""
    from dmclock_tpu.sim.config import parse_config_file
    cfg = parse_config_file("configs/dmc_sim_example.conf")
    assert_traces_equal(cfg, seed=12345)
