"""Full-scale acceptance-config parity through the TPU backend.

The north-star gate (BASELINE.json): the TPU engine must reproduce the
CPU ``dmc_sim`` request ordering on the REAL acceptance configs, not
scaled shapes.  The backend runs batched device launches (fused
ingest+decide, ``TpuPullPriorityQueue._jit_ingest_run``); the sim
drives it through the same discrete-event harness as the oracle, so
the full (time, server, client, phase, cost) trace must match row for
row.

The 100x100 stress config takes minutes (launch-latency bound at one
decision per service slot); it is gated behind DMCLOCK_FULLSCALE=1 so
the default suite stays fast.  `scripts/run_fullscale.py` (CI) runs it.
"""

import os

import pytest

from dmclock_tpu.sim.config import parse_config_file
from dmclock_tpu.sim.dmc_sim import run_sim

CONFIGS = os.path.join(os.path.dirname(__file__), "..", "configs")


def assert_fullscale_parity(conf_name, seed=12345):
    cfg = parse_config_file(os.path.join(CONFIGS, conf_name))
    cpu = run_sim(cfg, model="dmclock-delayed", seed=seed,
                  record_trace=True)
    tpu = run_sim(cfg, model="dmclock-tpu", seed=seed, record_trace=True)
    assert len(cpu.trace) == len(tpu.trace) > 0
    for i, (a, b) in enumerate(zip(cpu.trace, tpu.trace)):
        assert a == b, f"trace diverges at op {i}: cpu={a} tpu={b}"
    for cid in cpu.clients:
        ca, cb = cpu.clients[cid].stats, tpu.clients[cid].stats
        assert (ca.reservation_ops, ca.priority_ops) == \
            (cb.reservation_ops, cb.priority_ops)


@pytest.mark.slow
def test_fullscale_example():
    """configs/dmc_sim_example.conf (1 srv x 4 cli, 8000 ops): exact
    trace parity at full scale (~25s on CPU jax)."""
    assert_fullscale_parity("dmc_sim_example.conf")


@pytest.mark.skipif(not os.environ.get("DMCLOCK_FULLSCALE"),
                    reason="minutes-long; set DMCLOCK_FULLSCALE=1")
def test_fullscale_100th():
    """configs/dmc_sim_100th.conf (100 srv x 100 cli, 100k ops): exact
    trace parity at full scale."""
    assert_fullscale_parity("dmc_sim_100th.conf")
