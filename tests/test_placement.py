"""Shard rebalancing plane (lifecycle/placement.py; docs/LIFECYCLE.md
"Placement and migration").

The headline gates:

- **power-of-two-choices placement**: new registrations sample two
  shards from the checkpointed placement RNG and join the lower-
  backlog one; pinned scenarios (shard_skew) keep ``cid % S`` with
  ZERO draws, overrides win over pins, a DOWN sampled shard re-routes
  to the live choice, both-down defers one boundary -- all
  deterministic, all replayed bit-identically from ``encode/load``;
- **S=1 loop neutrality**: a 1-shard mesh churn job under
  ``placement="p2c"`` is BIT-IDENTICAL to the static path (p2c over
  one shard can only pick shard 0, and order = cid equals the
  take_order sequence at S=1);
- **the migration twin gate**: after the controller's ``migrate``
  rule moves quiet-since-start clients off the hot shard, the chain
  digest equals the run that had them placed on the destination from
  epoch 0 (same arrival RNG, overrides pinning the moved cids) --
  the canonical-digest proof that migration is placement-equivalent,
  not just plausible.  ``state_digest`` is deliberately NOT compared:
  slot layouts legitimately differ between the twins;
- **crash equivalence**: SIGKILL at ANY stage of the two-sided move
  (evicted -> handoff -> registered; the ``placement._migrate_hook``
  seam) replays the identical run from the previous checkpoint;
- **chaos composition**: churn + fault_plan is accepted under
  placement="p2c" (the DOWN-shard re-route path) and stays a loud
  ValueError under static routing -- the PR-15 rejection, now scoped.
"""

import dataclasses

import numpy as np
import pytest

from dmclock_tpu.lifecycle import churn as churn_mod
from dmclock_tpu.lifecycle import placement as placement_mod
from dmclock_tpu.lifecycle.placement import (PlacementMap,
                                             parse_placement,
                                             placement_pins)
from dmclock_tpu.robust import host_faults as HF
from dmclock_tpu.robust import supervisor as SV

# controller spec whose ONLY live rule is migrate: sync pinned at 1
# (staleness_up can't fire), backlog_hi parked sky-high (clamp_down
# can't), occ_lo 0 (compact can't); cooldown 8 spaces fires out
GATE_CTL = dict(sync_max=1, backlog_hi=10**9, occ_lo=0.0,
                hysteresis=1, cooldown=8,
                migrate_skew_hi=1.5, migrate_pick="cold",
                migrate_max=4)


def base_job(**over):
    kw = dict(engine="prefix", k=16, select_impl="sort",
              n=96, depth=6, ring=10, epochs=8, m=2, seed=5,
              arrival_lam=1.0, waves=2, ckpt_every=2,
              engine_loop="mesh", n_shards=1)
    kw.update(over)
    return SV.EpochJob(**kw)


def skew_job(**over):
    """The S=4 migration shape: shard_skew with a quiet tail (half
    the hot shard's ranks drained at zero completions -- the twin
    gate's provably placement-equivalent mover class)."""
    spec = churn_mod.make_spec("shard_skew", total_ids=64, seed=3,
                               cold_frac=0.5, cold_until=10**9)
    return base_job(n_shards=4, churn=spec, placement="p2c",
                    controller=GATE_CTL, **over)


_REFS: dict = {}


def migration_ref():
    """One cached S=4 run with real migrations (run A of the twin)."""
    if "A" not in _REFS:
        res = SV.run_job(skew_job())
        assert res.migrations > 0, \
            "migrate rule never fired -- the gate would be vacuous"
        _REFS["A"] = res
    return _REFS["A"]


# ----------------------------------------------------------------------
# PlacementMap unit behavior (no devices)
# ----------------------------------------------------------------------


class TestPlacementMapUnit:

    def test_parse_placement(self):
        assert parse_placement(None) == ("static", {})
        assert parse_placement("static") == ("static", {})
        assert parse_placement("p2c") == ("p2c", {})
        mode, ov = parse_placement(
            {"mode": "p2c", "overrides": {"37": 2}})
        assert mode == "p2c" and ov == {37: 2}
        with pytest.raises(ValueError):
            parse_placement("zipf")

    def test_p2c_deterministic_and_seeded(self):
        a = PlacementMap(4, 32, mode="p2c", seed=7)
        b = PlacementMap(4, 32, mode="p2c", seed=7)
        backlog = np.zeros(4, dtype=np.int64)
        a.place_batch(list(range(32)), backlog=backlog)
        b.place_batch(list(range(32)), backlog=backlog)
        assert np.array_equal(a.assign, b.assign)
        c = PlacementMap(4, 32, mode="p2c", seed=8)
        c.place_batch(list(range(32)), backlog=backlog)
        assert not np.array_equal(a.assign, c.assign)

    def test_p2c_prefers_lower_backlog(self):
        pm = PlacementMap(2, 64, mode="p2c", seed=1)
        backlog = np.asarray([10**6, 0], dtype=np.int64)
        pm.place_batch(list(range(64)), backlog=backlog)
        # both samples equal -> that shard regardless; otherwise the
        # empty shard wins every time
        assert (pm.assign == 1).sum() > (pm.assign == 0).sum()

    def test_pins_keep_static_routing_with_zero_draws(self):
        spec = churn_mod.make_spec("shard_skew", total_ids=32)
        pins = placement_pins(spec, 4)
        assert pins.all()
        pm = PlacementMap(4, 32, mode="p2c", seed=7, pins=pins)
        pm.place_batch(list(range(32)),
                       backlog=np.zeros(4, dtype=np.int64))
        assert np.array_equal(pm.assign, np.arange(32) % 4)
        assert pm.counters["p2c_draws"] == 0

    def test_no_pins_for_unpinned_scenarios(self):
        spec = churn_mod.make_spec("flash_crowd", total_ids=32)
        assert not placement_pins(spec, 4).any()

    def test_overrides_win_over_pins(self):
        spec = churn_mod.make_spec("shard_skew", total_ids=32)
        pm = PlacementMap(4, 32, mode="p2c", seed=7,
                          pins=placement_pins(spec, 4),
                          overrides={8: 3, 9: 2})
        pm.place_batch(list(range(32)),
                       backlog=np.zeros(4, dtype=np.int64))
        assert pm.shard_of(8) == 3 and pm.shard_of(9) == 2
        assert pm.shard_of(12) == 0          # still pinned
        assert pm.counters["overrides"] == 2

    def test_down_shard_reroutes_to_live_choice(self):
        pm = PlacementMap(2, 128, mode="p2c", seed=3)
        up = np.asarray([True, False])
        placed = pm.place_batch(list(range(128)),
                                backlog=np.zeros(2, dtype=np.int64),
                                up=up)
        # a (live, down) pair re-routes to the live sample; a
        # (down, down) pair -- possible at S=2 -- defers instead
        assert placed, "every pair deferred?"
        assert all(pm.shard_of(c) == 0 for c in placed)
        assert pm.counters["reroutes"] > 0
        assert pm.counters["defers"] == 128 - len(placed)
        assert len(pm.take_deferred()) == 128 - len(placed)

    def test_both_down_defers_one_boundary(self):
        pm = PlacementMap(2, 8, mode="p2c", seed=3)
        up = np.asarray([False, False])
        placed = pm.place_batch(list(range(8)),
                                backlog=np.zeros(2, dtype=np.int64),
                                up=up)
        assert placed == []
        assert pm.counters["defers"] == 8
        deferred = pm.take_deferred()
        assert deferred == list(range(8))
        assert pm.take_deferred() == []       # cleared on read
        # next boundary, shards back: the deferrals place normally
        placed = pm.place_batch(deferred,
                                backlog=np.zeros(2, dtype=np.int64))
        assert placed == deferred
        assert all(pm.shard_of(c) >= 0 for c in deferred)

    def test_rng_parity_reroute_vs_clean(self):
        """A DOWN shard changes the DESTINATION, never the draw
        count: the RNG stream stays aligned with the clean run."""
        a = PlacementMap(2, 64, mode="p2c", seed=9)
        b = PlacementMap(2, 64, mode="p2c", seed=9)
        a.place_batch(list(range(32)),
                      backlog=np.zeros(2, dtype=np.int64))
        b.place_batch(list(range(32)),
                      backlog=np.zeros(2, dtype=np.int64),
                      up=np.asarray([True, False]))
        assert a.counters["p2c_draws"] == b.counters["p2c_draws"]
        # post-divergence draws identical again
        a2 = a.place_batch([40], backlog=np.zeros(2, dtype=np.int64))
        b2 = b.place_batch([40], backlog=np.zeros(2, dtype=np.int64))
        assert a.assign[40] == b.assign[40]

    def test_plan_moves_excludes_src_and_down(self):
        pm = PlacementMap(4, 32, mode="p2c", seed=7)
        pm.place_batch(list(range(32)),
                       backlog=np.zeros(4, dtype=np.int64))
        backlog = np.asarray([100, 0, 0, 0], dtype=np.int64)
        up = np.asarray([True, True, False, False])
        cands = [c for c in range(32) if pm.shard_of(c) == 0]
        moves = pm.plan_moves(1, src=0, candidates=cands,
                              backlog=backlog, up=up, max_moves=2)
        assert len(moves) <= 2
        for cid, dst in moves:
            assert dst == 1                  # only live non-src shard
            assert pm.shard_of(cid) == 1     # assign updated
        assert pm.counters["migrations"] == len(moves)
        for row in pm.move_log():
            assert row[0] == 1 and row[2] == 0

    def test_encode_load_round_trip(self):
        pm = PlacementMap(4, 32, mode="p2c", seed=7)
        pm.place_batch(list(range(16)),
                       backlog=np.zeros(4, dtype=np.int64))
        cands = [c for c in range(16) if pm.shard_of(c) == 0]
        pm.plan_moves(3, src=0, candidates=cands,
                      backlog=np.asarray([9, 0, 0, 0]), max_moves=2)
        enc = pm.encode()
        pm2 = PlacementMap(4, 32, mode="p2c", seed=0)   # seed differs
        pm2.load(enc)
        assert np.array_equal(pm2.assign, pm.assign)
        assert pm2.counters == pm.counters
        assert pm2.move_log() == pm.move_log()
        # the RESTORED rng continues the original stream
        a = pm.place_batch([20], backlog=np.zeros(4, dtype=np.int64))
        b = pm2.place_batch([20], backlog=np.zeros(4, dtype=np.int64))
        assert pm.shard_of(20) == pm2.shard_of(20)


# ----------------------------------------------------------------------
# supervisor integration: validation, S=1 neutrality, the twin gate
# ----------------------------------------------------------------------


class TestPlacementSupervisor:

    def test_p2c_requires_mesh_churn(self):
        with pytest.raises(ValueError, match="placement"):
            SV.run_job(base_job(engine_loop="stream",
                                placement="p2c"))
        with pytest.raises(ValueError, match="placement"):
            SV.run_job(base_job(placement="p2c"))   # mesh, no churn

    def test_static_chaos_rejection_still_loud(self):
        """The PR-15 rejection pin, now scoped to static routing:
        churn + fault_plan without a placement map stays a loud
        ValueError (a registration routed to a DOWN shard would have
        no re-route path)."""
        spec = churn_mod.make_spec("flash_crowd", total_ids=32)
        with pytest.raises(ValueError, match="p2c"):
            SV.run_job(base_job(
                n_shards=4, churn=spec,
                fault_plan={"seed": 11, "p_dropout": 0.3}))

    def test_s1_p2c_is_loop_neutral(self):
        """p2c over ONE shard can only ever pick shard 0, and
        order = cid equals the take_order sequence at S=1 -- so the
        digest, metrics, and lifecycle snapshot are bit-identical to
        the static path."""
        spec = churn_mod.make_spec("flash_crowd", total_ids=32)
        a = SV.run_job(base_job(churn=spec))
        b = SV.run_job(base_job(churn=spec, placement="p2c"))
        assert a.digest == b.digest
        assert a.state_digest == b.state_digest
        assert np.array_equal(a.metrics, b.metrics)
        assert a.lifecycle == b.lifecycle
        assert b.placement == "p2c" and a.placement is None

    def test_migration_fires_and_logs(self):
        res = migration_ref()
        assert res.placement == "p2c"
        assert res.migrations == len(res.migration_log)
        assert res.placement_counters["migrations"] == res.migrations
        for bnd, cid, src, dst in res.migration_log:
            assert src == 0                    # off the hot shard
            assert dst in (1, 2, 3)
            assert cid % 4 == 0                # a hot-shard-owned id

    def test_migration_twin_gate(self):
        """THE tentpole gate: the post-migration run's chain digest
        equals the run that had the moved clients placed on their
        destinations from epoch 0 (placement overrides from run A's
        migration log; migrate rule disabled).  state_digest is NOT
        compared -- the twins' slot layouts legitimately differ."""
        a = migration_ref()
        ov = {str(cid): dst for _b, cid, _s, dst in a.migration_log}
        off = dict(GATE_CTL)
        off["migrate_skew_hi"] = 0.0
        b = SV.run_job(dataclasses.replace(
            skew_job(), placement={"mode": "p2c", "overrides": ov},
            controller=off))
        assert b.migrations == 0
        assert a.digest == b.digest
        assert b.placement_counters["overrides"] == len(ov)

    @pytest.mark.parametrize("stage",
                             ["evicted", "handoff", "registered"])
    def test_migration_crash_equivalence(self, stage, tmp_path):
        """SIGKILL at any stage of the two-sided move replays the
        identical run -- the journaled trigger + checkpointed
        placement RNG recompute the same move list from the previous
        checkpoint."""
        ref = migration_ref()
        fired = []

        def hook(s):
            if s == stage and not fired:
                fired.append(1)
                raise HF.HostKill(f"mid-migration:{stage}")

        old = placement_mod._migrate_hook
        placement_mod._migrate_hook = hook
        try:
            res = SV.run_supervised(skew_job(), tmp_path,
                                    HF.zero_host_plan())
        finally:
            placement_mod._migrate_hook = old
        assert fired, f"migrate hook never reached at {stage}"
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1
        assert res.migration_log == ref.migration_log

    def test_p2c_chaos_composes_and_source_down_is_masked(self):
        """Migration mid-chaos: a fault plan whose hot shard is DOWN
        at the first migrate-eligible boundary.  The composition must
        (a) be accepted at all (the scoped rejection), (b) never pick
        a down shard as migration source or destination, and (c) be
        deterministic -- two clean runs bit-equal."""
        from dmclock_tpu.robust import faults as F

        job0 = skew_job(epochs=8)
        # deterministic seed search: a plan with the hot shard down
        # at boundary 4 (the first migrate fire of the clean run)
        fault = None
        for seed in range(64):
            spec = {"seed": seed, "p_dropout": 0.5,
                    "mean_outage_steps": 2.0}
            plan = F.plan_from_spec(F.parse_fault_spec(spec),
                                    job0.epochs, job0.n_shards)
            if not plan.up[4, 0]:
                fault = spec
                break
        assert fault is not None
        job = dataclasses.replace(job0, fault_plan=fault)
        a = SV.run_job(job)
        b = SV.run_job(job)
        assert a.digest == b.digest
        assert a.migration_log == b.migration_log
        plan = F.plan_from_spec(F.parse_fault_spec(fault),
                                job.epochs, job.n_shards)
        for bnd, cid, src, dst in a.migration_log:
            row = plan.up[min(bnd, plan.up.shape[0] - 1)]
            assert row[src] and row[dst], \
                "moved through a DOWN shard"


# ----------------------------------------------------------------------
# migrated client x the other planes
# ----------------------------------------------------------------------


class TestMigratedClientWheel:

    def test_migration_reslot_adjust_equals_rebuild(self):
        """The two wheel halves of a migration at a fixed now: the
        source wheel adjusted over the departing slot equals the
        rebuild of the evicted state, and the destination wheel
        adjusted over the recycled slot -- now carrying the mover's
        QoS -- equals the rebuild of the registered state."""
        import jax.numpy as jnp

        from dmclock_tpu.core.timebase import NS_PER_SEC
        from dmclock_tpu.engine import fastpath as FP

        from test_calendar_bucketed import zipf64_state
        from test_calendar_wheel import _assert_wheel_equal

        state = zipf64_state(n=10, depth=32)
        now = jnp.int64(500 * NS_PER_SEC)
        c = 4
        onehot = jnp.arange(state.capacity) == c
        # source half: EVICT drains + deactivates the slot
        evicted = state._replace(
            active=state.active.at[c].set(False),
            depth=state.depth.at[c].set(0))
        w_src = FP.wheel_build(state, now, False)
        adj_out = FP.wheel_adjust(w_src, evicted, now, False, onehot)
        _assert_wheel_equal(adj_out,
                            FP.wheel_build(evicted, now, False))
        assert int(adj_out.slot[c]) == 3 * FP._WHEEL_BUCKETS
        # destination half: the recycled slot re-registers with the
        # mover's carried weight (a DIFFERENT contract than the slot
        # held before -- the contract-epoch bump)
        registered = evicted._replace(
            active=evicted.active.at[c].set(True),
            weight_inv=evicted.weight_inv.at[c].set(
                evicted.weight_inv[c] * 2))
        adj_in = FP.wheel_adjust(adj_out, registered, now, False,
                                 onehot)
        _assert_wheel_equal(adj_in,
                            FP.wheel_build(registered, now, False))

    def test_calendar_pressure_peaks_arm_migrate_rule(self):
        """Calendar engines drain ``state.depth`` at every deadline
        commit, so the BOUNDARY-TIME depth read that arms the migrate
        rule on prefix/chain is structurally zero there -- the rule
        used to be inert on calendar meshes.  The mid-epoch pressure
        peaks (``MeshGuarded.press`` -> ``ControlSignals.press_peak``/
        ``backlog_peak``) read the one instant where arrivals are
        queued but not yet drained, so the same skew job now fires on
        the wheel calendar too: migrations happen, every move leaves
        the hot shard, and the twin gate holds (cold movers placed on
        their destinations from epoch 0, rule disarmed, equal
        digest)."""
        job = skew_job(engine="calendar", k=4,
                       calendar_impl="wheel", ladder_levels=2)
        a = SV.run_job(job)
        assert a.migrations > 0, \
            "pressure peaks failed to arm the calendar migrate rule"
        assert a.migrations == len(a.migration_log)
        for _bnd, _cid, src, dst in a.migration_log:
            assert src == 0                # off the hot shard
            assert dst in (1, 2, 3)
        ov = {str(cid): dst for _b, cid, _s, dst in a.migration_log}
        off = dict(GATE_CTL)
        off["migrate_skew_hi"] = 0.0
        b = SV.run_job(dataclasses.replace(
            job, placement={"mode": "p2c", "overrides": ov},
            controller=off))
        assert b.migrations == 0
        assert a.digest == b.digest


class TestMigratedClientExplain:

    def _rows(self):
        """A migrated client's window log: two contract epochs (the
        destination REGISTER bumps it), limit-capped in both."""
        rows = []
        for seq, cep in ((0, 1), (1, 1), (2, 2), (3, 2)):
            rows.append({"client": 7, "seq": seq,
                         "contract_epoch": cep, "ops": 40,
                         "rate": 40.0, "limit": 40.0,
                         "reservation": 5.0, "share": 0.5,
                         "entitled_share": 0.5, "share_err": 0.0,
                         "backlog": 12, "resv_ops": 4,
                         "tardy_ops": 0, "resv_deficit": 0.0,
                         "resv_miss": False})
        rows.append({"client": 9, "seq": 0, "contract_epoch": 1,
                     "ops": 0, "rate": 0.0, "backlog": 0})
        return rows

    def test_attribution_survives_contract_epoch_bump(self):
        import importlib.util
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location(
            "explain", repo / "scripts" / "explain.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        wins = mod.client_windows(self._rows(), 7)
        # BOTH contract epochs' windows attribute as one client: the
        # migration handoff carries identity, not a fresh client
        assert len(wins) == 4
        assert {w["contract_epoch"] for w in wins} == {1, 2}
        att = mod.attribute(wins)
        assert att["cause"] == "limit_capped"
        assert att["scores"]["no_demand"] == 0.0
        # pre- and post-migration epochs attribute identically when
        # the windows are identical (epoch is identity metadata, not
        # an attribution input)
        pre = mod.attribute([w for w in wins
                             if w["contract_epoch"] == 1])
        post = mod.attribute([w for w in wins
                              if w["contract_epoch"] == 2])
        assert pre["scores"] == post["scores"]

    def test_exit_2_when_client_absent(self, tmp_path):
        import json
        import subprocess
        import sys
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        log = tmp_path / "slo.jsonl"
        log.write_text("\n".join(json.dumps(r)
                                 for r in self._rows()) + "\n")
        proc = subprocess.run(
            [sys.executable, str(repo / "scripts" / "explain.py"),
             "--slo", str(log), "--client", "12345"],
            capture_output=True, text=True)
        assert proc.returncode == 2
        proc = subprocess.run(
            [sys.executable, str(repo / "scripts" / "explain.py"),
             "--slo", str(log), "--client", "7"],
            capture_output=True, text=True)
        assert proc.returncode == 0
        assert "limit_capped" in proc.stdout
