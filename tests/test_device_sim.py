"""Device-resident batch sim: behavioral QoS validation.

The device sim is a batch-synchronous MODEL (see device_sim.py
docstring), so these tests pin dmClock's defining behaviors --
weight-proportional sharing, reservation floors, limit caps -- plus
determinism, rather than event-exact traces (the engine kernels it is
built from are trace-pinned elsewhere: tests/test_tpu_engine.py,
test_sim_tpu_fullscale.py, test_parallel.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.sim import device_sim as DS
from dmclock_tpu.sim.config import ClientGroup, ServerGroup, SimConfig


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return DS.make_mesh(8)


def make_cfg(groups, *, iops=160.0, soft_limit=False):
    return SimConfig(client_groups=len(groups), server_groups=1,
                     server_random_selection=False,
                     server_soft_limit=soft_limit,
                     cli_group=groups,
                     srv_group=[ServerGroup(server_count=8,
                                            server_iops=iops,
                                            server_threads=1)])


def run_fixed(cfg, mesh, launches=4, slices=32):
    sim, spec = DS.init_device_sim(cfg)
    sim = DS.shard_device_sim(sim, mesh)
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh, slices=slices))
    for _ in range(launches):
        sim = step(sim)
    served = np.asarray(sim.served_resv) + np.asarray(sim.served_prop)
    return sim, spec, served.sum(axis=0)  # [C] per-client completions


def group_slices(groups):
    out, ci = [], 0
    for g in groups:
        out.append(slice(ci, ci + g.client_count))
        ci += g.client_count
    return out


def test_weight_shares_under_saturation(mesh8):
    """Backlogged weight-1 vs weight-2 clients split capacity ~1:2
    (reference pull_weight behavior at sim scale)."""
    groups = [
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=8),
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=2.0, client_server_select_range=8),
    ]
    _sim, _spec, served = run_fixed(make_cfg(groups), mesh8)
    g = group_slices(groups)
    r1, r2 = served[g[0]].sum(), served[g[1]].sum()
    assert r1 > 0 and r2 > 0
    ratio = r2 / r1
    assert 1.7 < ratio < 2.3, f"weight 1:2 served ratio {ratio:.2f}"


def test_reservation_floor_under_contention(mesh8):
    """A tiny-weight client group with a reservation keeps its floor
    while heavy-weight traffic saturates the cluster."""
    groups = [
        ClientGroup(client_count=4, client_total_ops=100000,
                    client_iops_goal=200, client_outstanding_ops=100,
                    client_reservation=40.0, client_limit=0.0,
                    client_weight=0.01, client_server_select_range=8),
        ClientGroup(client_count=12, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=4.0, client_server_select_range=8),
    ]
    sim, _spec, served = run_fixed(make_cfg(groups), mesh8)
    g = group_slices(groups)
    t_s = int(sim.t) / 1e9
    floor_rate = served[g[0]].sum() / 4 / t_s
    assert floor_rate >= 0.8 * 40.0, \
        f"reserved client rate {floor_rate:.1f} < floor 40"


def test_limit_caps_rate(mesh8):
    """A limited client group is capped near its limit even with spare
    capacity and demand above it (AtLimit.WAIT).  Rate measured over
    the run's second half: requests carry the delta from their SEND
    time (the piggyback protocol), so an initial in-flight window of
    stale-delta requests legitimately overshoots before the tracker
    feedback binds -- in the reference too."""
    groups = [
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=120, client_outstanding_ops=16,
                    client_reservation=0.0, client_limit=40.0,
                    client_weight=1.0, client_server_select_range=8),
    ]
    cfg = make_cfg(groups, iops=400.0)
    sim, spec = DS.init_device_sim(cfg)
    sim = DS.shard_device_sim(sim, mesh8)
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh8, slices=32))
    for _ in range(8):
        sim = step(sim)
    t1 = int(sim.t)
    s1 = (np.asarray(sim.served_resv)
          + np.asarray(sim.served_prop)).sum()
    for _ in range(8):
        sim = step(sim)
    t2 = int(sim.t)
    s2 = (np.asarray(sim.served_resv)
          + np.asarray(sim.served_prop)).sum()
    rate = (s2 - s1) / 8 / ((t2 - t1) / 1e9)
    assert rate <= 1.2 * 40.0, f"limited rate {rate:.1f} > cap 40"
    assert rate >= 0.6 * 40.0, f"limited rate {rate:.1f} far below cap"


def test_deterministic(mesh8):
    groups = [
        ClientGroup(client_count=8, client_total_ops=500,
                    client_iops_goal=100, client_outstanding_ops=32,
                    client_reservation=20.0, client_limit=60.0,
                    client_weight=1.0, client_server_select_range=4),
    ]
    _s1, _sp1, a = run_fixed(make_cfg(groups), mesh8, launches=2)
    _s2, _sp2, b = run_fixed(make_cfg(groups), mesh8, launches=2)
    assert (a == b).all()


def test_cli_runs(mesh8, capsys):
    from dmclock_tpu.sim.device_sim import run_device_sim
    groups = [
        ClientGroup(client_count=8, client_total_ops=200,
                    client_iops_goal=100, client_outstanding_ops=32,
                    client_reservation=20.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=4),
    ]
    _sim, _spec, report = run_device_sim(make_cfg(groups), mesh=mesh8)
    assert "total ops: 1600" in report
