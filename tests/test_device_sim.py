"""Device-resident batch sim: behavioral QoS validation.

The device sim is a batch-synchronous MODEL (see device_sim.py
docstring), so these tests pin dmClock's defining behaviors --
weight-proportional sharing, reservation floors, limit caps -- plus
determinism, rather than event-exact traces (the engine kernels it is
built from are trace-pinned elsewhere: tests/test_tpu_engine.py,
test_sim_tpu_fullscale.py, test_parallel.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.sim import device_sim as DS
from dmclock_tpu.sim.config import ClientGroup, ServerGroup, SimConfig


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return DS.make_mesh(8)


def make_cfg(groups, *, iops=160.0, soft_limit=False):
    return SimConfig(client_groups=len(groups), server_groups=1,
                     server_random_selection=False,
                     server_soft_limit=soft_limit,
                     cli_group=groups,
                     srv_group=[ServerGroup(server_count=8,
                                            server_iops=iops,
                                            server_threads=1)])


def run_fixed(cfg, mesh, launches=4, slices=32):
    sim, spec = DS.init_device_sim(cfg)
    sim = DS.shard_device_sim(sim, mesh)
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh, slices=slices))
    for _ in range(launches):
        sim = step(sim)
    served = np.asarray(sim.served_resv) + np.asarray(sim.served_prop)
    return sim, spec, served.sum(axis=0)  # [C] per-client completions


def group_slices(groups):
    out, ci = [], 0
    for g in groups:
        out.append(slice(ci, ci + g.client_count))
        ci += g.client_count
    return out


@pytest.mark.slow
def test_weight_shares_under_saturation(mesh8):
    """Backlogged weight-1 vs weight-2 clients split capacity ~1:2
    (reference pull_weight behavior at sim scale)."""
    groups = [
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=8),
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=2.0, client_server_select_range=8),
    ]
    _sim, _spec, served = run_fixed(make_cfg(groups), mesh8)
    g = group_slices(groups)
    r1, r2 = served[g[0]].sum(), served[g[1]].sum()
    assert r1 > 0 and r2 > 0
    ratio = r2 / r1
    assert 1.7 < ratio < 2.3, f"weight 1:2 served ratio {ratio:.2f}"


def test_reservation_floor_under_contention(mesh8):
    """A tiny-weight client group with a reservation keeps its floor
    while heavy-weight traffic saturates the cluster."""
    groups = [
        ClientGroup(client_count=4, client_total_ops=100000,
                    client_iops_goal=200, client_outstanding_ops=100,
                    client_reservation=40.0, client_limit=0.0,
                    client_weight=0.01, client_server_select_range=8),
        ClientGroup(client_count=12, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=4.0, client_server_select_range=8),
    ]
    sim, _spec, served = run_fixed(make_cfg(groups), mesh8)
    g = group_slices(groups)
    t_s = int(sim.t) / 1e9
    floor_rate = served[g[0]].sum() / 4 / t_s
    assert floor_rate >= 0.8 * 40.0, \
        f"reserved client rate {floor_rate:.1f} < floor 40"


def test_limit_caps_rate(mesh8):
    """A limited client group is capped near its limit even with spare
    capacity and demand above it (AtLimit.WAIT).  Rate measured over
    the run's second half: requests carry the delta from their SEND
    time (the piggyback protocol), so an initial in-flight window of
    stale-delta requests legitimately overshoots before the tracker
    feedback binds -- in the reference too."""
    groups = [
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=120, client_outstanding_ops=16,
                    client_reservation=0.0, client_limit=40.0,
                    client_weight=1.0, client_server_select_range=8),
    ]
    cfg = make_cfg(groups, iops=400.0)
    sim, spec = DS.init_device_sim(cfg)
    sim = DS.shard_device_sim(sim, mesh8)
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh8, slices=32))
    for _ in range(8):
        sim = step(sim)
    t1 = int(sim.t)
    s1 = (np.asarray(sim.served_resv)
          + np.asarray(sim.served_prop)).sum()
    for _ in range(8):
        sim = step(sim)
    t2 = int(sim.t)
    s2 = (np.asarray(sim.served_resv)
          + np.asarray(sim.served_prop)).sum()
    rate = (s2 - s1) / 8 / ((t2 - t1) / 1e9)
    assert rate <= 1.2 * 40.0, f"limited rate {rate:.1f} > cap 40"
    assert rate >= 0.6 * 40.0, f"limited rate {rate:.1f} far below cap"


def test_deterministic(mesh8):
    groups = [
        ClientGroup(client_count=8, client_total_ops=500,
                    client_iops_goal=100, client_outstanding_ops=32,
                    client_reservation=20.0, client_limit=60.0,
                    client_weight=1.0, client_server_select_range=4),
    ]
    _s1, _sp1, a = run_fixed(make_cfg(groups), mesh8, launches=2)
    _s2, _sp2, b = run_fixed(make_cfg(groups), mesh8, launches=2)
    assert (a == b).all()


def test_cli_runs(mesh8, capsys):
    from dmclock_tpu.sim.device_sim import run_device_sim
    groups = [
        ClientGroup(client_count=8, client_total_ops=200,
                    client_iops_goal=100, client_outstanding_ops=32,
                    client_reservation=20.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=4),
    ]
    _sim, _spec, report = run_device_sim(make_cfg(groups), mesh=mesh8)
    assert "total ops: 1600" in report


@pytest.mark.slow
def test_random_server_selection(mesh8):
    """v2: device-side counter-RNG selection (reference random policy,
    simulate.h:401-444) -- load spreads over every server and weight
    shares still hold."""
    groups = [
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=8),
        ClientGroup(client_count=8, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=2.0, client_server_select_range=8),
    ]
    cfg = make_cfg(groups)
    cfg.server_random_selection = True
    sim, _spec, served = run_fixed(cfg, mesh8)
    per_server = (np.asarray(sim.served_resv)
                  + np.asarray(sim.served_prop)).sum(axis=1)  # [S]
    assert (per_server > 0).all(), \
        f"random selection must reach every server: {per_server}"
    g = group_slices(groups)
    ratio = served[g[1]].sum() / served[g[0]].sum()
    assert 1.6 < ratio < 2.4, f"weight 1:2 ratio {ratio:.2f}"


@pytest.mark.slow
def test_multi_thread_servers(mesh8):
    """v2: threads > 1 keeps the aggregate iops model (op_time =
    threads/iops): total throughput matches the single-thread run."""
    groups = [
        ClientGroup(client_count=16, client_total_ops=100000,
                    client_iops_goal=400, client_outstanding_ops=100,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=8),
    ]
    cfg1 = make_cfg(groups, iops=320.0)
    cfg2 = make_cfg(groups, iops=320.0)
    cfg2.srv_group[0].server_threads = 2
    _s1, spec1, served1 = run_fixed(cfg1, mesh8)
    _s2, spec2, served2 = run_fixed(cfg2, mesh8)
    assert spec2.q_per_slice == 2 * spec1.q_per_slice
    # same virtual time span per launch batch: slices x slice_ns with
    # slice_ns doubled but serves per slice doubled too -> total ops
    # per unit virtual time equal; compare service rates
    t1, t2 = int(_s1.t), int(_s2.t)
    rate1 = served1.sum() / t1
    rate2 = served2.sum() / t2
    assert abs(rate2 - rate1) / rate1 < 0.1, \
        f"aggregate-rate model broken: {rate1:.2e} vs {rate2:.2e}"


def _prefix_vs_scan(cfg, mesh8, q):
    """Run identical workloads through the prefix serve loop and the
    q-step serial scan; the looped prefix batches commit the exact
    serial stream capped at the slice budget, so per-(server, client,
    phase) service must be IDENTICAL, not merely close."""
    import dataclasses
    sim, spec = DS.init_device_sim(cfg)
    spec_big = dataclasses.replace(
        spec, q_per_slice=q, slice_ns=spec.op_time_ns * q)
    spec_scan = dataclasses.replace(spec_big, force_scan=True)
    # the radix selection backend must be indistinguishable here too
    # (same loop, vmapped over servers under shard_map)
    spec_radix = dataclasses.replace(spec_big, select_impl="radix")

    outs = []
    for spc in (spec_big, spec_scan, spec_radix):
        sm = DS.shard_device_sim(sim, mesh8)
        step = jax.jit(functools.partial(DS.device_sim_step, spec=spc,
                                         mesh=mesh8, slices=8))
        for _ in range(3):
            sm = step(sm)
        outs.append((np.asarray(sm.served_resv),
                     np.asarray(sm.served_prop)))
    (ar, ap), (br, bp), (rr, rp) = outs
    assert ar.sum() + ap.sum() > 0
    assert np.array_equal(ar, br), \
        f"resv-phase service diverges: {ar.sum()} vs {br.sum()}"
    assert np.array_equal(ap, bp), \
        f"prop-phase service diverges: {ap.sum()} vs {bp.sum()}"
    assert np.array_equal(ar, rr) and np.array_equal(ap, rp), \
        "radix selection diverges from sort in the device sim"


@pytest.mark.slow
def test_prefix_serve_mode_matches_scan(mesh8):
    """Throughput shapes (q >= 256) serve via prefix-commit batches;
    the outcome must exactly match the q-step serial scan."""
    groups = [
        ClientGroup(client_count=512, client_total_ops=10**9,
                    client_iops_goal=20000, client_outstanding_ops=200,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0 + (1 % 3),
                    client_server_select_range=8),
    ]
    _prefix_vs_scan(make_cfg(groups, iops=200000.0), mesh8, 256)


@pytest.mark.slow
def test_prefix_serve_skewed_population_matches_scan(mesh8):
    """Eligible population far below q (select_range=1 pins each
    client to ONE server: 8 reachable clients per server vs q=256): a
    single prefix batch serves each client at most once and would lose
    the rest of the slice; the batch loop must recover it exactly."""
    groups = [
        ClientGroup(client_count=64, client_total_ops=10**9,
                    client_iops_goal=40000, client_outstanding_ops=200,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=1),
    ]
    _prefix_vs_scan(make_cfg(groups, iops=200000.0), mesh8, 256)


def test_guard_trips_checked(mesh8):
    """The prefix rebase guards are a CHECKED invariant, not an
    assumption: corrupting the state init_device_sim validated (a
    served cost past the int32 sort payload) must trip the counter,
    and run_device_sim's check must raise on it."""
    groups = [
        ClientGroup(client_count=64, client_total_ops=10**9,
                    client_iops_goal=20000, client_outstanding_ops=200,
                    client_reservation=0.0, client_limit=0.0,
                    client_weight=1.0, client_server_select_range=8),
    ]
    import dataclasses
    cfg = make_cfg(groups, iops=200000.0)
    sim, spec = DS.init_device_sim(cfg)
    spec = dataclasses.replace(spec, q_per_slice=256,
                               slice_ns=spec.op_time_ns * 256)
    sim = DS.shard_device_sim(sim, mesh8)
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh8, slices=4))
    sim = step(sim)
    assert int(np.asarray(sim.guard_trips)) == 0

    # break the init-time validation after the fact: request costs
    # past 2^31 (what the init assert statically excludes) -- fresh
    # ingests install them on candidate heads, so the very next serve
    # batch sees the oversized sort payload
    bad_cost = jnp.full_like(sim.load.cost, jnp.int64(1) << 32)
    sim = sim._replace(load=sim.load._replace(cost=bad_cost))
    sim = step(sim)
    assert int(np.asarray(sim.guard_trips)) > 0, \
        "corrupted cost payload never tripped the guard counter"

    # and the driver-level check raises on a tripped counter
    with pytest.raises(RuntimeError, match="rebase-guard"):
        DS.check_guard_trips(sim)


@pytest.mark.slow
def test_prefix_serve_allow_soft_limit_matches_scan(mesh8):
    """AtLimit::Allow (soft limit) on the prefix path: the reference's
    own stress shape (dmc_sim_100th.conf sets server_soft_limit=true,
    all weights positive) must serve identically to the q-step serial
    scan -- the round-4 engine excluded Allow from the fastpath
    entirely."""
    groups = [
        ClientGroup(client_count=256, client_total_ops=10**9,
                    client_iops_goal=20000, client_outstanding_ops=200,
                    client_reservation=20.0, client_limit=60.0,
                    client_weight=1.0 + (1 % 3),
                    client_server_select_range=8),
    ]
    cfg = make_cfg(groups, iops=200000.0, soft_limit=True)
    spec = DS._make_spec(cfg)
    assert spec.allow_limit_break and spec.all_weights_positive
    _prefix_vs_scan(cfg, mesh8, 256)


def test_allow_weight_zero_keeps_scan(mesh8):
    """The Allow-fastpath restriction: a weight-0 client group forces
    the serial scan (per-client classification cannot express the
    reference's ready-weight-0 reservation-order fallback)."""
    groups = [
        ClientGroup(client_count=32, client_total_ops=1000,
                    client_iops_goal=2000, client_outstanding_ops=20,
                    client_reservation=10.0, client_limit=30.0,
                    client_weight=0.0, client_server_select_range=8),
    ]
    cfg = make_cfg(groups, iops=200000.0, soft_limit=True)
    spec = DS._make_spec(cfg)
    assert spec.allow_limit_break and not spec.all_weights_positive
