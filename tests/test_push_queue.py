"""Push-mode queue tests (reference PushPriorityQueue semantics,
dmclock_server.h:1504-1797): autonomous dispatch via handle_f, the
can_handle gate, and the sched-ahead timed wakeup."""

import threading
import time

from dmclock_tpu.core import (ClientInfo, Phase, PushPriorityQueue,
                              ReqParams, sec_to_ns)


def wait_until(pred, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return pred()


class TestPushQueue:
    def test_immediate_dispatch(self):
        handled = []
        q = PushPriorityQueue(lambda c: ClientInfo(0, 1, 0),
                              can_handle_f=lambda: True,
                              handle_f=lambda c, r, p, cost:
                              handled.append((c, r, p, cost)),
                              run_gc_thread=False)
        try:
            q.add_request("req1", 7, ReqParams())
            assert wait_until(lambda: len(handled) == 1)
            assert handled[0][0] == 7
            assert handled[0][2] is Phase.PRIORITY
        finally:
            q.shutdown()

    def test_can_handle_gates_dispatch(self):
        handled = []
        gate = {"open": False}
        q = PushPriorityQueue(lambda c: ClientInfo(0, 1, 0),
                              can_handle_f=lambda: gate["open"],
                              handle_f=lambda c, r, p, cost:
                              handled.append(r),
                              run_gc_thread=False)
        try:
            q.add_request("r", 1, ReqParams())
            time.sleep(0.05)
            assert handled == []
            gate["open"] = True
            q.request_completed()  # server signals capacity
            assert wait_until(lambda: handled == ["r"])
        finally:
            q.shutdown()

    def test_sched_ahead_timed_wakeup(self):
        # a future-limited request is dispatched by the sched-ahead
        # thread once its limit restores, without further prompting
        handled = []
        q = PushPriorityQueue(lambda c: ClientInfo(0, 1, 10),
                              can_handle_f=lambda: True,
                              handle_f=lambda c, r, p, cost:
                              handled.append((r, time.monotonic())),
                              at_limit=__import__(
                                  "dmclock_tpu").AtLimit.WAIT,
                              run_gc_thread=False)
        try:
            now = sec_to_ns(time.time())
            # two requests: limit 10/s -> second eligible ~0.1s later
            q.add_request("a", 1, ReqParams(), time_ns=now)
            q.add_request("b", 1, ReqParams(), time_ns=now)
            assert wait_until(lambda: len(handled) == 2, timeout_s=3.0)
        finally:
            q.shutdown()

    def test_early_wakeup_does_not_drop_deadline(self):
        # regression (code-review finding): a notify with a new earlier
        # deadline while blocked must not discard the armed wakeup even
        # if can_handle_f is False at that instant
        handled = []
        gate = {"open": True}
        q = PushPriorityQueue(lambda c: ClientInfo(0, 1, 5),
                              can_handle_f=lambda: gate["open"],
                              handle_f=lambda c, r, p, cost:
                              handled.append(r),
                              run_gc_thread=False)
        try:
            now = sec_to_ns(time.time())
            q.add_request("a", 1, ReqParams(), time_ns=now)
            q.add_request("b", 1, ReqParams(), time_ns=now)  # future ~0.2s
            gate["open"] = False
            # poke the queue while the deadline is armed: previously
            # this consumed the armed time inside the closed gate
            q.request_completed()
            gate["open"] = True
            assert wait_until(lambda: len(handled) == 2, timeout_s=3.0), \
                f"handled={handled}"
        finally:
            q.shutdown()


class TestShutdown:
    def test_shutdown_joins_threads(self):
        q = PushPriorityQueue(lambda c: ClientInfo(0, 1, 0),
                              can_handle_f=lambda: True,
                              handle_f=lambda *a: None,
                              run_gc_thread=True, check_time_s=0.05,
                              idle_age_s=0.2, erase_age_s=0.4)
        time.sleep(0.15)  # let the GC thread tick at least once
        q.shutdown()
        assert q.finishing
