"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware.  The environment's TPU boot shim force-
selects its platform via ``jax.config`` at interpreter startup, so env
vars alone don't stick -- override the config the same way, before any
backend is used.  x64 stays enabled because the canonical tag algebra is
int64 nanoseconds.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)
