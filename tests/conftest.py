"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware.  The environment's TPU boot shim force-
selects its platform via ``jax.config`` at interpreter startup, so env
vars alone don't stick -- override the config the same way, before any
backend is used.  x64 stays enabled because the canonical tag algebra is
int64 nanoseconds.
"""

import gc
import os

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the CPU device count is an XLA flag, read when the
    # backend initializes (no backend exists yet at conftest time)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """One long pytest process accumulates XLA CPU compile state until
    late-suite tests stall for tens of minutes or the compiler
    segfaults (observed at ~140 tests in).  Dropping every compiled
    program between modules keeps each module's footprint fresh; the
    shared-kernel recompiles this forces are far cheaper than the
    stall."""
    yield
    jax.clear_caches()
    # the compile plane's instrumented caches hold AOT executables
    # OUTSIDE jax's own caches -- drop those too, or the relief this
    # fixture exists for never reaches the module jit caches
    from dmclock_tpu.obs import compile_plane
    compile_plane.clear_compiled()
    gc.collect()
