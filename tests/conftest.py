"""Test configuration.

JAX runs on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware, and x64 is enabled because the canonical
tag algebra is int64 nanoseconds.  Env vars must be set before the first
jax import anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
