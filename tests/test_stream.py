"""Always-on streaming serve loop (engine.stream /
robust.guarded.run_stream_chunk_guarded / robust.supervisor
``engine_loop="stream"`` / engine.queue.pull_batch_stream).

The headline gate: the stream loop's decision digest, final state,
and metric totals are BIT-IDENTICAL to the round-based engine on all
three epoch engines and every fast-path combination (radix, tag32,
bucketed) -- with the double-buffered superwave pregen (wave T+1
drawn while the device runs wave T) producing the exact digest of
sequential generation, including across a SIGKILL-mid-stream resumed
supervised run.  Plus: the guard-trip chunk fallback, chunk_bounds
layout, epoch-view field parity, and the queue's chunked pull."""

import dataclasses

import numpy as np
import pytest

from dmclock_tpu.engine import stream as ST
from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.robust import host_faults as HF
from dmclock_tpu.robust import supervisor as SV

BASE = dict(n=96, depth=6, ring=10, epochs=5, m=2, seed=5,
            arrival_lam=1.0, waves=2, ckpt_every=2)
# epochs=5 with ckpt_every=2 gives chunk layout 2+2+1: full chunks AND
# a remainder chunk both exercised by every test below
JOBS = {
    "prefix-sort": SV.EpochJob(engine="prefix", k=16,
                               select_impl="sort", **BASE),
    "prefix-radix": SV.EpochJob(engine="prefix", k=16,
                                select_impl="radix", **BASE),
    "prefix-tag32": SV.EpochJob(engine="prefix", k=16, tag_width=32,
                                **BASE),
    "chain": SV.EpochJob(engine="chain", chain_depth=3, k=8, **BASE),
    "calendar-minstop": SV.EpochJob(engine="calendar", k=4,
                                    calendar_impl="minstop", **BASE),
    "calendar-bucketed": SV.EpochJob(engine="calendar", k=4,
                                     calendar_impl="bucketed",
                                     ladder_levels=2, **BASE),
    "calendar-wheel": SV.EpochJob(engine="calendar", k=4,
                                  calendar_impl="wheel",
                                  ladder_levels=2, **BASE),
}

_REFS: dict = {}
_SREFS: dict = {}


def ref_of(name: str) -> SV.SupervisedResult:
    """Cached round-loop reference (sequential superwave generation,
    per-epoch launches) per engine/fast-path combination."""
    if name not in _REFS:
        _REFS[name] = SV.run_job(JOBS[name])
    return _REFS[name]


def stream_job(name: str, **over) -> SV.EpochJob:
    return dataclasses.replace(JOBS[name], engine_loop="stream",
                               **over)


def stream_ref_of(name: str) -> SV.SupervisedResult:
    """Cached bare stream run of the unmodified job -- shared between
    the digest gate and the crash tests (deterministic, so a cached
    run IS a fresh run)."""
    if name not in _SREFS:
        _SREFS[name] = SV.run_job(stream_job(name))
    return _SREFS[name]


def assert_stream_equals_round(s: SV.SupervisedResult,
                               r: SV.SupervisedResult) -> None:
    assert s.digest == r.digest, "decision digest diverged"
    assert s.state_digest == r.state_digest, "final state diverged"
    assert s.decisions == r.decisions
    assert np.array_equal(np.asarray(s.metrics),
                          np.asarray(r.metrics)), \
        (s.metrics, r.metrics)


class TestStreamDigestGate:
    # one engine per family stays in the quick sweep; the remaining
    # fast-path combinations are slow-marked for the tier-1 wall
    # budget (scripts/run_tests.sh runs them; the ci.sh streaming
    # smoke gates the full matrix too)
    @pytest.mark.parametrize("name", [
        "prefix-sort", "chain", "calendar-minstop",
        pytest.param("prefix-radix", marks=pytest.mark.slow),
        pytest.param("prefix-tag32", marks=pytest.mark.slow),
        pytest.param("calendar-bucketed", marks=pytest.mark.slow),
        pytest.param("calendar-wheel", marks=pytest.mark.slow),
    ])
    def test_stream_bit_identical_to_round(self, name):
        """The tentpole gate: fused ingest+serve chunks with
        double-buffered pregen == per-epoch round launches,
        bit-for-bit, on every engine x fast-path combination."""
        r = ref_of(name)
        assert r.decisions > 0
        s = stream_ref_of(name)
        assert_stream_equals_round(s, r)
        # a run whose ROUND reference never tripped a guard must stay
        # on the fused path end to end; a run that legitimately trips
        # (this shape's tag32 job resumes on int64 in round mode too)
        # must fall back -- slower, never divergent, and counted
        met = np.asarray(r.metrics)
        round_trips = int(met[obsdev.MET_REBASE_FALLBACKS]) \
            + int(met[obsdev.MET_GUARD_TRIPS])
        if round_trips == 0:
            assert s.stream_fallbacks == 0, \
                "a clean run must never leave the fused path"
        else:
            assert s.stream_fallbacks > 0

    @pytest.mark.slow
    def test_stream_telemetry_bit_identical(self):
        """Histograms + ledger + flight ring ride the chunk carry and
        must match the round loop's accumulators exactly."""
        tele = dict(with_hists=True, with_ledger=True,
                    flight_records=16)
        r = SV.run_job(dataclasses.replace(JOBS["calendar-bucketed"],
                                           **tele))
        s = SV.run_job(stream_job("calendar-bucketed", **tele))
        assert_stream_equals_round(s, r)
        # telemetry compared bit-for-bit by the shared gate
        SV.assert_crash_equivalent(s, r)

    def test_no_ingest_stream(self):
        """arrival_lam=0 streams serve-only chunks (the ingest leg is
        statically absent, not zero-count)."""
        r = SV.run_job(dataclasses.replace(JOBS["prefix-sort"],
                                           arrival_lam=0.0))
        s = SV.run_job(stream_job("prefix-sort", arrival_lam=0.0))
        assert_stream_equals_round(s, r)

    def test_single_epoch_chunks(self):
        """ckpt_every=1 degenerates to one-epoch chunks -- still the
        fused program, still bit-identical."""
        r = SV.run_job(dataclasses.replace(JOBS["chain"],
                                           ckpt_every=1))
        s = SV.run_job(stream_job("chain", ckpt_every=1))
        assert_stream_equals_round(s, r)


class TestChunkBounds:
    def test_boundary_layout_matches_checkpoint_schedule(self):
        # saves land at (e+1) % every == 0 or e+1 == epochs; chunks
        # must end exactly there
        assert list(ST.chunk_bounds(0, 5, 2)) == [(0, 2), (2, 4),
                                                  (4, 5)]
        assert list(ST.chunk_bounds(0, 4, 2)) == [(0, 2), (2, 4)]
        assert list(ST.chunk_bounds(2, 5, 2)) == [(2, 4), (4, 5)]
        assert list(ST.chunk_bounds(0, 3, 8)) == [(0, 3)]
        assert list(ST.chunk_bounds(5, 5, 2)) == []

    def test_resume_start_mid_layout(self):
        # a resume landing on any snapshot epoch re-enters the same
        # boundary grid
        assert list(ST.chunk_bounds(4, 9, 4)) == [(4, 8), (8, 9)]


class TestEpochViews:
    def test_views_are_the_round_result_classes(self):
        """The digest walks result fields via hasattr: the stream
        views must BE the epoch-result classes with identically-typed
        arrays, or the chain digest could silently change shape."""
        from dmclock_tpu.engine import fastpath
        from dmclock_tpu.robust.guarded import run_epoch_guarded, \
            run_stream_chunk_guarded

        job = JOBS["prefix-sort"]
        state = SV._job_state(job)
        g = run_stream_chunk_guarded(
            state, 0, np.zeros((2, job.n), dtype=np.int32),
            engine="prefix", epochs=2, m=job.m, k=job.k,
            dt_epoch_ns=job.dt_epoch_ns, waves=job.waves)
        assert g.stream_fallback == 0
        (view,) = g.epochs[0]
        assert isinstance(view, fastpath.PrefixEpoch)
        ref = run_epoch_guarded(SV._job_state(job), job.dt_epoch_ns,
                                engine="prefix", m=job.m, k=job.k,
                                with_metrics=False)
        (round_ep,) = ref.results
        for field in ("count", "slot", "phase", "cost", "lb"):
            a = np.asarray(getattr(view, field))
            b = np.asarray(getattr(round_ep, field))
            assert a.dtype == b.dtype, field
            assert a.shape == b.shape, field


class TestStreamFallback:
    def test_tag32_window_trip_falls_back_bit_identical(self):
        """tag_spread_ns past 2^31 trips the tag32 rebase window every
        epoch: the fused chunk cannot run the int64 resume mid-scan,
        so it must discard and re-run on the round path -- slower,
        never divergent, and counted."""
        trip = dict(tag_width=32, tag_spread_ns=1 << 33)
        r = SV.run_job(dataclasses.replace(JOBS["prefix-sort"],
                                           **trip))
        s = SV.run_job(stream_job("prefix-sort", **trip))
        assert_stream_equals_round(s, r)
        assert s.stream_fallbacks > 0
        assert r.stream_fallbacks == 0


class TestStreamCrashEquivalence:
    """SIGKILL mid-stream: the double buffer draws chunk T+1's waves
    before boundary T's snapshot is written, so the persisted RNG
    state MUST be the post-chunk-T snapshot, not the live generator --
    these gates are what pin that discipline."""

    @pytest.mark.parametrize("name", ["prefix-sort", "chain",
                                      "calendar-bucketed"])
    def test_sigkill_mid_stream_resumes_bit_identical(self, tmp_path,
                                                      name):
        job = stream_job(name)
        ref = stream_ref_of(name)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(ref.decisions // 2,))
        out = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(out, ref)
        assert out.restarts == 1

    @pytest.mark.slow
    @pytest.mark.parametrize("frac", [4, 3, 1])
    def test_kill_points_across_the_chunk_grid(self, tmp_path, frac):
        job = stream_job("prefix-sort", with_hists=True,
                         with_ledger=True, flight_records=8)
        ref = SV.run_job(job)
        kill_at = max(ref.decisions // frac, 1)
        plan = HF.HostFaultPlan(kill_at_decisions=(kill_at,))
        out = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(out, ref)

    @pytest.mark.slow
    def test_zero_host_fault_stream_gate(self, tmp_path):
        """Supervisor-wrapped stream + empty plan == bare stream,
        bit-identical including the metric vector and telemetry."""
        job = stream_job("calendar-minstop", with_hists=True,
                         with_ledger=True, flight_records=8)
        ref = SV.run_job(job)
        out = SV.run_supervised(job, tmp_path, HF.zero_host_plan())
        SV.assert_crash_equivalent(out, ref)
        assert out.restarts == 0
        assert np.array_equal(out.metrics, ref.metrics)
        assert out.metrics[obsdev.MET_LADDER_STEPS] == 0
        assert out.metrics[obsdev.MET_SUPERVISOR_RESUMES] == 0

    @pytest.mark.slow
    def test_spawn_sigkill_mid_stream(self, tmp_path):
        """Spawn mode: a REAL SIGKILL mid-stream in a child
        interpreter, resumed from the rotation checkpoint."""
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        job = stream_job("prefix-sort")
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(ref.decisions // 2,))
        out = SV.run_supervised(job, tmp_path, plan, mode="spawn")
        SV.assert_crash_equivalent(out, ref)
        assert out.restarts == 1


class TestQueueStream:
    def test_pull_batch_stream_matches_sequential(self):
        """chunks sequential pull_batch launches == one
        pull_batch_stream launch, decision for decision."""
        from dmclock_tpu.core.qos import ClientInfo
        from dmclock_tpu.core.recs import ReqParams
        from dmclock_tpu.engine import TpuPullPriorityQueue

        infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 2, 0),
                 3: ClientInfo(5, 1, 0)}

        def build():
            q = TpuPullPriorityQueue(lambda c: infos[c], capacity=8,
                                     ring_capacity=16)
            for c in infos:
                for j in range(6):
                    q.add_request(("r", c, j), c, ReqParams(1, 1),
                                  time_ns=1000 + j, cost=1)
            return q

        t0, dt, chunks, k = 10 ** 9, 10 ** 8, 3, 4
        qa, qb = build(), build()
        streamed = qa.pull_batch_stream(t0, dt, chunks, k)
        sequential = [qb.pull_batch(t0 + c * dt, k)
                      for c in range(chunks)]
        assert len(streamed) == chunks
        for got, want in zip(streamed, sequential):
            assert [(p.type, p.client, p.request, p.phase, p.cost)
                    for p in got] == \
                [(p.type, p.client, p.request, p.phase, p.cost)
                 for p in want]
        # the host mirrors must track identically too
        assert qa.reserv_sched_count == qb.reserv_sched_count
        assert qa.prop_sched_count == qb.prop_sched_count
        assert np.array_equal(qa._ledger, qb._ledger)
