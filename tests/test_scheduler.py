"""Oracle scheduler behavioral tests.

Scenario coverage modeled on the reference suite
(``test/test_dmclock_server.cc``): virtual-time injection everywhere
(no sleeps), white-box inspection of queue internals, and behavioral
QoS-ratio checks.  Times are int64 ns; ``S`` is one second.
"""

import errno

import pytest

from dmclock_tpu.core import (AtLimit, ClientInfo, MAX_TAG, NS_PER_SEC,
                              NextReqType, Phase, PullPriorityQueue,
                              ReqParams, sec_to_ns)

S = NS_PER_SEC


def make_queue(infos, **kwargs):
    """Queue whose client_info_f looks up the given dict of ClientInfo."""
    kwargs.setdefault("run_gc_thread", False)
    return PullPriorityQueue(lambda c: infos[c], **kwargs)


def drain(q, now_ns, max_pulls=10_000):
    """Pull until not returning; list of (client, phase, cost)."""
    out = []
    for _ in range(max_pulls):
        pr = q.pull_request(now_ns)
        if not pr.is_retn():
            break
        out.append((pr.client, pr.phase, pr.cost))
    return out


class TestBasicAccounting:
    def test_empty_queue(self):
        q = make_queue({1: ClientInfo(1, 1, 1)})
        assert q.empty()
        assert q.client_count() == 0
        assert q.request_count() == 0

    def test_add_and_counts(self):
        q = make_queue({1: ClientInfo(1, 1, 1), 2: ClientInfo(1, 1, 1)})
        assert q.add_request("a", 1, ReqParams(), time_ns=1 * S) == 0
        assert q.add_request("b", 1, ReqParams(), time_ns=1 * S) == 0
        assert q.add_request("c", 2, ReqParams(), time_ns=1 * S) == 0
        assert not q.empty()
        assert q.client_count() == 2
        assert q.request_count() == 3

    def test_request_payload_roundtrip(self):
        q = make_queue({7: ClientInfo(0, 1, 0)})
        payload = {"op": "write", "len": 4096}
        q.add_request(payload, 7, ReqParams(), time_ns=1 * S)
        pr = q.pull_request(10 * S)
        assert pr.is_retn()
        assert pr.request is payload
        assert pr.client == 7
        assert pr.cost == 1


class TestQosRatios:
    def test_pull_weight_ratio(self):
        # weight 1:2 => 2:4 of 6 pulls
        # (model: reference pull_weight :822-874); a large base time
        # keeps organic tags away from the wall-time floor, as the
        # reference achieves by using get_time()
        T0 = 1000 * S
        infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 2, 0)}
        q = make_queue(infos)
        for i in range(10):
            q.add_request(("c1", i), 1, ReqParams(1, 1), time_ns=T0)
            q.add_request(("c2", i), 2, ReqParams(1, 1), time_ns=T0)
        pulls = [q.pull_request(T0) for _ in range(6)]
        counts = {1: 0, 2: 0}
        for pr in pulls:
            assert pr.is_retn()
            assert pr.phase is Phase.PRIORITY
            counts[pr.client] += 1
        assert counts == {1: 2, 2: 4}

    def test_pull_reservation_ratio(self):
        # reservation 2:1 => 4:2 of 6 pulls
        # (model: reference pull_reservation :877-929)
        T0 = 1000 * S
        infos = {1: ClientInfo(2, 0, 0), 2: ClientInfo(1, 0, 0)}
        q = make_queue(infos)
        for i in range(10):
            q.add_request(("c1", i), 1, ReqParams(1, 1), time_ns=T0)
            q.add_request(("c2", i), 2, ReqParams(1, 1), time_ns=T0)
        pulls = [q.pull_request(T0 + 100 * S) for _ in range(6)]
        counts = {1: 0, 2: 0}
        for pr in pulls:
            assert pr.is_retn()
            assert pr.phase is Phase.RESERVATION
            counts[pr.client] += 1
        assert counts == {1: 4, 2: 2}
        assert q.reserv_sched_count == 6
        assert q.prop_sched_count == 0

    def test_cost_weighting(self):
        # a cost-3 client advances its tags 3x as fast -> gets 1/3 the ops
        T0 = 1000 * S
        infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
        q = make_queue(infos)
        for i in range(12):
            q.add_request(("c1", i), 1, ReqParams(), time_ns=T0, cost=1)
            q.add_request(("c2", i), 2, ReqParams(), time_ns=T0, cost=3)
        pulls = [q.pull_request(2000 * S) for _ in range(8)]
        counts = {1: 0, 2: 0}
        for pr in pulls:
            counts[pr.client] += 1
        assert counts == {1: 6, 2: 2}


class TestStateMachine:
    def test_pull_none(self):
        # (model: reference pull_none :1184-1205)
        q = make_queue({1: ClientInfo(1, 1, 1)})
        pr = q.pull_request(sec_to_ns(1e9) + 100 * S)
        assert pr.is_none()

    def test_pull_future(self):
        # (model: reference pull_future :1208-1236): r=1 w=0 l=1,
        # request arrives 100s in the future -> future(arrival)
        q = make_queue({52: ClientInfo(1, 0, 1)})
        now = 1000 * S
        assert q.add_request("r", 52, ReqParams(1, 1),
                             time_ns=now + 100 * S) == 0
        pr = q.pull_request(now)
        assert pr.is_future()
        assert pr.when_ready == now + 100 * S

    def test_pull_future_limit_break_weight(self):
        # AtLimit.ALLOW serves the future request now via weight
        q = make_queue({52: ClientInfo(0, 1, 1)}, at_limit=AtLimit.ALLOW)
        now = 1000 * S
        q.add_request("r", 52, ReqParams(1, 1), time_ns=now + 100 * S)
        pr = q.pull_request(now)
        assert pr.is_retn()
        assert pr.client == 52
        assert pr.phase is Phase.PRIORITY

    def test_pull_future_limit_break_reservation(self):
        q = make_queue({52: ClientInfo(1, 0, 1)}, at_limit=AtLimit.ALLOW)
        now = 1000 * S
        q.add_request("r", 52, ReqParams(1, 1), time_ns=now + 100 * S)
        pr = q.pull_request(now)
        assert pr.is_retn()
        assert pr.client == 52
        assert pr.phase is Phase.RESERVATION

    def test_ready_and_under_limit(self):
        # (model: reference ready_and_under_limit :1120-1181)
        # limit 1 op/s gates the weight phase
        q = make_queue({1: ClientInfo(0, 1, 1)})
        q.add_request("a", 1, ReqParams(), time_ns=1 * S)
        q.add_request("b", 1, ReqParams(), time_ns=1 * S)
        # limit tags: 1s, 2s
        pr = q.pull_request(1 * S)
        assert pr.is_retn() and pr.request == "a"
        pr = q.pull_request(1 * S)
        assert pr.is_future()
        assert pr.when_ready == 2 * S
        pr = q.pull_request(2 * S)
        assert pr.is_retn() and pr.request == "b"


class TestWaitAtLimit:
    def test_pull_wait_at_limit(self):
        # (model: reference pull_wait_at_limit :1363-1471)
        infos = {52: ClientInfo(1, 2, 100), 8: ClientInfo(1, 1, 2)}
        q = make_queue(infos)
        now = 2000 * S
        add_time = now - 1 * S
        old_time = add_time
        for i in range(50):
            assert q.add_request(("c1", i), 52, ReqParams(1, 1),
                                 time_ns=add_time) == 0
            assert q.add_request(("c2", i), 8, ReqParams(1, 1),
                                 time_ns=add_time) == 0
            add_time += S // 100
        assert q.client_count() == 2
        assert q.request_count() == 100

        counts = {52: 0, 8: 0}
        # first two pulls come from the reservation queue, one each
        for _ in range(2):
            pr = q.pull_request(now)
            assert pr.is_retn()
            assert pr.phase is Phase.RESERVATION
            counts[pr.client] += 1
        assert counts == {52: 1, 8: 1}
        assert q.request_count() == 98

        # next 50 pulls: all remaining c1 requests + exactly one from c2
        for _ in range(50):
            pr = q.pull_request(now)
            assert pr.is_retn()
            assert pr.phase is Phase.PRIORITY
            counts[pr.client] += 1
        assert counts == {52: 50, 8: 2}
        assert q.request_count() == 48

        # c2 is over its limit: future at old_time + 2s exactly
        pr = q.pull_request(now)
        assert pr.is_future()
        assert pr.when_ready == old_time + 2 * S

        # once the limit restores, c2 is served again
        pr = q.pull_request(old_time + 2 * S)
        assert pr.is_retn()
        assert pr.client == 8
        assert q.request_count() == 47


class TestReject:
    def test_reject_at_limit(self):
        # (model: reference pull_reject_at_limit :1301-1337); immediate
        # tag calc; rejected requests still advance the limit tag
        q = make_queue({52: ClientInfo(0, 1, 1)}, at_limit=AtLimit.REJECT)
        assert q.add_request("a", 52, ReqParams(), time_ns=1 * S) == 0
        assert q.add_request("b", 52, ReqParams(), time_ns=2 * S) == 0
        assert q.add_request("c", 52, ReqParams(), time_ns=3 * S) == 0
        # too soon
        assert q.add_request("d", 52, ReqParams(),
                             time_ns=int(3.9 * S)) == errno.EAGAIN
        # the rejected request still counted against the limit
        assert q.add_request("e", 52, ReqParams(),
                             time_ns=4 * S) == errno.EAGAIN
        assert q.add_request("f", 52, ReqParams(), time_ns=6 * S) == 0

    def test_reject_threshold(self):
        # (model: reference pull_reject_threshold :1340-1360): passing a
        # bare threshold implies AtLimit.REJECT
        q = make_queue({52: ClientInfo(0, 1, 1)}, at_limit=3 * S)
        assert q.at_limit is AtLimit.REJECT
        for expected in (0, 0, 0, 0):
            assert q.add_request("x", 52, ReqParams(), time_ns=1 * S) \
                == expected
        assert q.add_request("x", 52, ReqParams(),
                             time_ns=1 * S) == errno.EAGAIN
        assert q.add_request("x", 52, ReqParams(), time_ns=3 * S) == 0

    def test_reject_incompatible_with_delayed(self):
        # (model: reference death test + assert :856-857)
        with pytest.raises(AssertionError):
            make_queue({1: ClientInfo(0, 1, 1)}, at_limit=AtLimit.REJECT,
                       delayed_tag_calc=True)


class TestDelayedTagCalc:
    def test_delayed_uses_latest_delta(self):
        # Delayed mode tags a request only when it reaches the head,
        # using the client's LATEST delta/rho (reference :277-280,
        # :1021-1036).  Immediate mode uses each request's own params.
        infos = {1: ClientInfo(0, 1, 0)}
        qd = make_queue(infos, delayed_tag_calc=True)
        qd.add_request("r1", 1, ReqParams(0, 0), time_ns=1 * S)
        qd.add_request("r2", 1, ReqParams(3, 0), time_ns=1 * S)
        qd.add_request("r3", 1, ReqParams(9, 0), time_ns=1 * S)
        qd.pull_request(1 * S)
        # white-box: r2's tag was computed at pop time with cur_delta=9
        rec = qd.client_map[1]
        # head tag: prev_p(1s) + 1s * (9 + 1) = 11s
        assert rec.next_request().tag.proportion == 11 * S

        qi = make_queue(infos, delayed_tag_calc=False)
        qi.add_request("r1", 1, ReqParams(0, 0), time_ns=1 * S)
        qi.add_request("r2", 1, ReqParams(3, 0), time_ns=1 * S)
        qi.add_request("r3", 1, ReqParams(9, 0), time_ns=1 * S)
        qi.pull_request(1 * S)
        rec = qi.client_map[1]
        # immediate: r2 tagged at add with its own delta=3 -> 1 + 4 = 5s
        assert rec.next_request().tag.proportion == 5 * S

    def test_delayed_zero_tag_until_head(self):
        q = make_queue({1: ClientInfo(0, 1, 0)}, delayed_tag_calc=True)
        q.add_request("r1", 1, ReqParams(), time_ns=1 * S)
        q.add_request("r2", 1, ReqParams(), time_ns=1 * S)
        rec = q.client_map[1]
        assert rec.requests[0].tag.proportion == 1 * S  # head: real tag
        assert rec.requests[1].tag.proportion == 0      # body: zero tag


class TestReduceReservationTags:
    def test_weight_service_pays_reservation_debt(self):
        # a weight-phase pop subtracts r_inv*(cost+rho) from the
        # client's queued reservation tags (reference :1077-1111)
        q = make_queue({1: ClientInfo(1, 1, 0)})
        q.add_request("a", 1, ReqParams(), time_ns=0)
        q.add_request("b", 1, ReqParams(), time_ns=0)
        rec = q.client_map[1]
        assert rec.requests[0].tag.reservation == 1 * S
        assert rec.requests[1].tag.reservation == 2 * S
        # pull at now=0.5s: reservation (1s) not yet due -> weight phase
        pr = q.pull_request(S // 2)
        assert pr.phase is Phase.PRIORITY
        # remaining request's reservation reduced by 1s*(1+0)
        assert rec.requests[0].tag.reservation == 1 * S
        assert rec.prev_tag.reservation == 1 * S

    def test_reservation_phase_does_not_reduce(self):
        q = make_queue({1: ClientInfo(1, 1, 0)})
        q.add_request("a", 1, ReqParams(), time_ns=0)
        q.add_request("b", 1, ReqParams(), time_ns=0)
        rec = q.client_map[1]
        pr = q.pull_request(10 * S)  # reservation due
        assert pr.phase is Phase.RESERVATION
        assert rec.requests[0].tag.reservation == 2 * S


class TestRemovalApis:
    def test_remove_by_req_filter(self):
        # (model: reference remove_by_req_filter* :373-605)
        q = make_queue({1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)})
        for i in range(5):
            q.add_request(("c1", i), 1, ReqParams(), time_ns=0)
            q.add_request(("c2", i), 2, ReqParams(), time_ns=0)
        removed = []

        def filt(req):
            if req[1] % 2 == 0:
                removed.append(req)
                return True
            return False

        assert q.remove_by_req_filter(filt)
        assert q.request_count() == 4
        assert len(removed) == 6
        # forward visit order within each client
        assert [r for r in removed if r[0] == "c1"] == \
            [("c1", 0), ("c1", 2), ("c1", 4)]

    def test_remove_by_req_filter_backwards(self):
        q = make_queue({1: ClientInfo(0, 1, 0)})
        for i in range(4):
            q.add_request(i, 1, ReqParams(), time_ns=0)
        seen = []
        q.remove_by_req_filter(lambda r: (seen.append(r), True)[1],
                               visit_backwards=True)
        assert seen == [3, 2, 1, 0]
        assert q.request_count() == 0

    def test_remove_by_client(self):
        # (model: reference remove_by_client :608-681)
        q = make_queue({1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)})
        for i in range(3):
            q.add_request(("c1", i), 1, ReqParams(), time_ns=0)
            q.add_request(("c2", i), 2, ReqParams(), time_ns=0)
        acc = []
        q.remove_by_client(1, accum=acc.append)
        assert acc == [("c1", 0), ("c1", 1), ("c1", 2)]
        assert q.request_count() == 3
        q.remove_by_client(2, reverse=True, accum=acc.append)
        assert acc[3:] == [("c2", 2), ("c2", 1), ("c2", 0)]
        q.remove_by_client(99)  # unknown client: no-op


class TestClientInfoUpdates:
    def test_update_client_info(self):
        # (model: reference update_client_info :932-1018)
        infos = {1: ClientInfo(0, 1, 0)}
        q = make_queue(infos)
        q.add_request("a", 1, ReqParams(), time_ns=0)
        infos[1].update(0, 4, 0)  # in-place rate change
        q.update_client_info(1)
        q.pull_request(10 * S)
        q.add_request("b", 1, ReqParams(), time_ns=0)
        rec = q.client_map[1]
        # new tag advances at 0.25s per op from prev 1s
        assert rec.requests[-1].tag.proportion == int(1.25 * S)

    def test_dynamic_cli_info(self):
        # (model: reference dynamic_cli_info_f :1021-1114): with
        # dynamic lookup the info function is consulted on every use
        calls = []
        info_a = ClientInfo(0, 1, 0)
        info_b = ClientInfo(0, 4, 0)

        def info_f(c):
            calls.append(c)
            return info_a if len(calls) <= 2 else info_b

        q = PullPriorityQueue(info_f, dynamic_cli_info=True,
                              run_gc_thread=False)
        q.add_request("a", 1, ReqParams(), time_ns=0)   # call 1 (create) + call 2 (tag)
        q.pull_request(10 * S)
        q.add_request("b", 1, ReqParams(), time_ns=0)   # call 3+ -> info_b
        rec = q.client_map[1]
        assert rec.requests[-1].tag.proportion == int(1.25 * S)


class TestIdleReactivation:
    def test_prop_delta_on_reactivation(self):
        # an idle client returning competes from the lowest active
        # proportion tag, not its stale one (reference :937-985)
        infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
        q = make_queue(infos)
        # client 1 busy: tags run ahead to ~100s
        for i in range(100):
            q.add_request(("c1", i), 1, ReqParams(), time_ns=0)
        for _ in range(50):
            q.pull_request(1000 * S)
        rec1 = q.client_map[1]
        assert rec1.next_request().tag.proportion == 51 * S
        # client 2 arrives fresh at t=0: would get tag ~1s and starve
        # client 1 for 50 ops without the prop_delta shift
        q.add_request(("c2", 0), 2, ReqParams(), time_ns=0)
        rec2 = q.client_map[2]
        assert rec2.prop_delta == 51 * S  # lowest active tag - time
        # interleaved service from here on, not 50 consecutive c2 pulls
        pulls = [q.pull_request(1000 * S).client for _ in range(4)]
        assert set(pulls) == {1, 2}


class TestGc:
    def _fake_clock(self):
        state = {"t": 0.0}

        def clock():
            return state["t"]

        return state, clock

    def test_idle_then_erase(self):
        # (model: reference client_idle_erase :100-185, with an
        # injected monotonic clock instead of sleeps)
        state, clock = self._fake_clock()
        q = make_queue({1: ClientInfo(1, 1, 0)}, idle_age_s=300,
                       erase_age_s=600, check_time_s=60,
                       monotonic_clock=clock)
        q.add_request("a", 1, ReqParams(), time_ns=0)
        q.pull_request(10 * S)
        q.do_clean()  # mark (t=0, tick=1)
        rec = q.client_map[1]
        assert not rec.idle

        state["t"] = 400.0
        q.do_clean()  # idle_point from mark at t=0
        assert rec.idle
        assert q.client_count() == 1

        state["t"] = 700.0
        q.do_clean()  # erase_point from mark at t=0
        assert q.client_count() == 0

    def test_erase_max_bounds_work(self):
        state, clock = self._fake_clock()
        q = make_queue({i: ClientInfo(1, 1, 0) for i in range(10)},
                       idle_age_s=10, erase_age_s=20, check_time_s=5,
                       erase_max=3, monotonic_clock=clock)
        for i in range(10):
            q.add_request("r", i, ReqParams(), time_ns=0)
            q.pull_request(10 * S)
        q.do_clean()
        state["t"] = 25.0
        q.do_clean()  # erase capped at 3 per pass
        assert q.client_count() == 7
        state["t"] = 26.0
        q.do_clean()
        assert q.client_count() == 4


class TestSchedulingInvariants:
    def test_interleaved_add_pull_monotone_service(self):
        # fuzz-ish determinism check: same inputs -> same outputs
        infos = {i: ClientInfo(i % 3, 1 + (i % 2), 0) for i in range(8)}

        def run():
            q = make_queue(infos)
            trace = []
            t = 0
            for step in range(400):
                c = (step * 7) % 8
                delta = step % 3
                q.add_request(step, c, ReqParams(delta, min(step % 2, delta)),
                              time_ns=t, cost=1 + step % 2)
                if step % 2:
                    pr = q.pull_request(t)
                    if pr.is_retn():
                        trace.append((pr.client, pr.request, pr.phase))
                t += S // 200
            trace.extend(drain(q, t + 100 * S))
            return trace

        t1, t2 = run(), run()
        assert t1 == t2
        assert len(t1) >= 400  # everything eventually served
