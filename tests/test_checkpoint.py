"""Checkpoint/resume: a snapshotted queue or device sim must continue
bit-exactly from where it left off."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.engine import TpuPullPriorityQueue, init_state
from dmclock_tpu.utils.checkpoint import (queue_state_dict,
                                          restore_pytree,
                                          restore_queue_state,
                                          save_pytree)

S = 10**9


def test_queue_checkpoint_resume(tmp_path):
    infos = {c: ClientInfo(10, 1.0 + c % 3, 0) for c in range(6)}

    def build():
        return TpuPullPriorityQueue(lambda c: infos[c], capacity=16,
                                    ring_capacity=16)

    q = build()
    for i in range(12):
        q.add_request(("r", i), i % 6, ReqParams(1, 1),
                      time_ns=(i + 1) * S // 4)
    # serve a few, snapshot mid-stream
    pre = [q.pull_request(4 * S) for _ in range(5)]
    assert all(p.is_retn() for p in pre)
    host = queue_state_dict(q)          # flushes; MUST precede the
    save_pytree(tmp_path / "engine", q.state)  # device-state save

    # continue the original
    rest_orig = [q.pull_request(5 * S) for _ in range(7)]

    # resume a fresh queue from the snapshot
    q2 = build()
    q2.state = restore_pytree(tmp_path / "engine", q2.state)
    restore_queue_state(q2, host)
    rest_resumed = [q2.pull_request(5 * S) for _ in range(7)]

    for a, b in zip(rest_orig, rest_resumed):
        assert (a.type, a.client, a.phase, a.cost) == \
            (b.type, b.client, b.phase, b.cost)


def test_device_sim_checkpoint_resume(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from dmclock_tpu.sim import device_sim as DS
    from dmclock_tpu.sim.config import (ClientGroup, ServerGroup,
                                        SimConfig)

    cfg = SimConfig(
        client_groups=1, server_groups=1,
        server_random_selection=False, server_soft_limit=False,
        cli_group=[ClientGroup(client_count=8, client_total_ops=10000,
                               client_iops_goal=100,
                               client_outstanding_ops=16,
                               client_reservation=20.0,
                               client_limit=0.0, client_weight=1.0,
                               client_server_select_range=4)],
        srv_group=[ServerGroup(server_count=8, server_iops=160,
                               server_threads=1)])
    mesh = DS.make_mesh(8)
    sim, spec = DS.init_device_sim(cfg)
    sim = DS.shard_device_sim(sim, mesh)
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh, slices=16))
    sim = step(sim)
    save_pytree(tmp_path / "sim", sim)

    cont = step(step(sim))

    fresh, _ = DS.init_device_sim(cfg)
    fresh = DS.shard_device_sim(fresh, mesh)
    resumed = restore_pytree(tmp_path / "sim", fresh)
    resumed = DS.shard_device_sim(resumed, mesh)
    resumed = step(step(resumed))

    for f in ("served_resv", "served_prop", "t"):
        assert (np.asarray(getattr(cont, f))
                == np.asarray(getattr(resumed, f))).all(), f
