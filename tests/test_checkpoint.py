"""Checkpoint/resume: a snapshotted queue or device sim must continue
bit-exactly from where it left off -- and a TORN snapshot (truncated,
bit-flipped, sidecar-less, killed mid-save) must never be restorable
(docs/ROBUSTNESS.md)."""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.engine import TpuPullPriorityQueue, init_state
from dmclock_tpu.utils import checkpoint as ckpt_mod
from dmclock_tpu.utils.checkpoint import (CheckpointCorruptError,
                                          queue_state_dict,
                                          restore_pytree,
                                          restore_pytree_rotating,
                                          restore_queue_state,
                                          save_pytree,
                                          save_pytree_rotating)

S = 10**9


def test_queue_checkpoint_resume(tmp_path):
    infos = {c: ClientInfo(10, 1.0 + c % 3, 0) for c in range(6)}

    def build():
        return TpuPullPriorityQueue(lambda c: infos[c], capacity=16,
                                    ring_capacity=16)

    q = build()
    for i in range(12):
        q.add_request(("r", i), i % 6, ReqParams(1, 1),
                      time_ns=(i + 1) * S // 4)
    # serve a few, snapshot mid-stream
    pre = [q.pull_request(4 * S) for _ in range(5)]
    assert all(p.is_retn() for p in pre)
    host = queue_state_dict(q)          # flushes; MUST precede the
    save_pytree(tmp_path / "engine", q.state)  # device-state save

    # continue the original
    rest_orig = [q.pull_request(5 * S) for _ in range(7)]

    # resume a fresh queue from the snapshot
    q2 = build()
    q2.state = restore_pytree(tmp_path / "engine", q2.state)
    restore_queue_state(q2, host)
    rest_resumed = [q2.pull_request(5 * S) for _ in range(7)]

    for a, b in zip(rest_orig, rest_resumed):
        assert (a.type, a.client, a.phase, a.cost) == \
            (b.type, b.client, b.phase, b.cost)


def test_device_sim_checkpoint_resume(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from dmclock_tpu.sim import device_sim as DS
    from dmclock_tpu.sim.config import (ClientGroup, ServerGroup,
                                        SimConfig)

    cfg = SimConfig(
        client_groups=1, server_groups=1,
        server_random_selection=False, server_soft_limit=False,
        cli_group=[ClientGroup(client_count=8, client_total_ops=10000,
                               client_iops_goal=100,
                               client_outstanding_ops=16,
                               client_reservation=20.0,
                               client_limit=0.0, client_weight=1.0,
                               client_server_select_range=4)],
        srv_group=[ServerGroup(server_count=8, server_iops=160,
                               server_threads=1)])
    mesh = DS.make_mesh(8)
    sim, spec = DS.init_device_sim(cfg)
    sim = DS.shard_device_sim(sim, mesh)
    step = jax.jit(functools.partial(DS.device_sim_step, spec=spec,
                                     mesh=mesh, slices=16))
    sim = step(sim)
    save_pytree(tmp_path / "sim", sim)

    cont = step(step(sim))

    fresh, _ = DS.init_device_sim(cfg)
    fresh = DS.shard_device_sim(fresh, mesh)
    resumed = restore_pytree(tmp_path / "sim", fresh)
    resumed = DS.shard_device_sim(resumed, mesh)
    resumed = step(step(resumed))

    for f in ("served_resv", "served_prop", "t"):
        assert (np.asarray(getattr(cont, f))
                == np.asarray(getattr(resumed, f))).all(), f


# ----------------------------------------------------------------------
# corruption: a damaged snapshot must never restore
# ----------------------------------------------------------------------

def _state(mark: int):
    st = init_state(16, 8)
    return st._replace(head_resv=st.head_resv.at[3].set(mark))


def _like():
    return init_state(16, 8)


def test_restore_truncated_file(tmp_path):
    p = tmp_path / "snap"
    save_pytree(p, _state(111))
    raw = p.read_bytes()
    p.write_bytes(raw[:len(raw) // 2])
    with pytest.raises(CheckpointCorruptError):
        restore_pytree(p, _like())


def _flip_payload_byte(path, mark: int) -> None:
    """Flip one byte INSIDE stored leaf data (found via the int64
    marker's byte pattern) -- a flip in zip header padding would be
    semantically dead and rightly restorable."""
    raw = bytearray(open(path, "rb").read())
    pat = int(mark).to_bytes(8, "little")
    idx = bytes(raw).find(pat)
    assert idx > 0, "marker bytes not found in snapshot"
    raw[idx] ^= 0xFF
    open(path, "wb").write(bytes(raw))


def test_restore_flipped_byte(tmp_path):
    p = tmp_path / "snap"
    mark = 0x0123456789AB
    save_pytree(p, _state(mark))
    _flip_payload_byte(p, mark)
    with pytest.raises(CheckpointCorruptError):
        restore_pytree(p, _like())


def test_restore_missing_sidecar(tmp_path):
    p = tmp_path / "snap"
    save_pytree(p, _state(111))
    os.unlink(str(p) + ".sha256")
    with pytest.raises(CheckpointCorruptError, match="sidecar"):
        restore_pytree(p, _like())


def test_restore_shape_mismatch(tmp_path):
    p = tmp_path / "snap"
    save_pytree(p, _state(111))
    with pytest.raises(CheckpointCorruptError):
        restore_pytree(p, init_state(32, 8))


def test_restore_from_rotation_skips_corrupt_newest(tmp_path):
    rot = tmp_path / "rot"
    mark = 0x0123456789AB
    save_pytree_rotating(rot, _state(1))
    newest = save_pytree_rotating(rot, _state(mark))
    # corrupt the newest entry; restore must fall back to entry 1
    _flip_payload_byte(newest, mark)
    tree, path = restore_pytree_rotating(rot, _like())
    assert int(tree.head_resv[3]) == 1
    assert path.endswith("ckpt-00000001")


def test_rotation_prunes_to_keep(tmp_path):
    rot = tmp_path / "rot"
    for i in range(6):
        save_pytree_rotating(rot, _state(i), keep=3)
    names = sorted(n for n in os.listdir(rot)
                   if not n.endswith(".sha256"))
    assert names == [f"ckpt-{i:08d}" for i in (4, 5, 6)]
    tree, _ = restore_pytree_rotating(rot, _like())
    assert int(tree.head_resv[3]) == 5


def test_rotation_empty_raises(tmp_path):
    with pytest.raises(CheckpointCorruptError, match="no intact"):
        restore_pytree_rotating(tmp_path / "nothing", _like())


# ----------------------------------------------------------------------
# kill-during-save: no crash point leaves a restorable-but-torn state
# ----------------------------------------------------------------------

class _SimulatedKill(BaseException):
    """BaseException so nothing in the save path can swallow it --
    the closest in-process stand-in for SIGKILL."""


@pytest.mark.parametrize("stage", ["data_written", "data_synced",
                                   "data_renamed", "sidecar_written"])
def test_kill_during_save_restores_previous_intact(tmp_path, stage):
    rot = tmp_path / "rot"
    save_pytree_rotating(rot, _state(7))       # the intact predecessor

    def kill_at(s, stage=stage):
        if s == stage:
            raise _SimulatedKill(stage)

    ckpt_mod._crash_hook = kill_at
    try:
        with pytest.raises(_SimulatedKill):
            save_pytree_rotating(rot, _state(8))
    finally:
        ckpt_mod._crash_hook = None
    # restore never sees the torn entry: it lands on the predecessor
    tree, path = restore_pytree_rotating(rot, _like())
    assert int(tree.head_resv[3]) == 7, \
        f"kill at {stage} left a restorable torn snapshot"
    assert path.endswith("ckpt-00000001")
    # and a clean retry of the same save then wins
    save_pytree_rotating(rot, _state(8))
    tree, _ = restore_pytree_rotating(rot, _like())
    assert int(tree.head_resv[3]) == 8


@pytest.mark.parametrize("stage", ["data_written", "data_synced",
                                   "data_renamed", "sidecar_written"])
def test_kill_during_inplace_overwrite_keeps_old_snapshot(tmp_path,
                                                          stage):
    """Non-rotating save over an EXISTING path: a kill at any commit
    stage (including between the data and sidecar renames) must leave
    the previous snapshot restorable via the hard-linked .prev pair."""
    p = tmp_path / "snap"
    save_pytree(p, _state(7))

    def kill_at(s, stage=stage):
        if s == stage:
            raise _SimulatedKill(stage)

    ckpt_mod._crash_hook = kill_at
    try:
        with pytest.raises(_SimulatedKill):
            save_pytree(p, _state(8))
    finally:
        ckpt_mod._crash_hook = None
    tree = restore_pytree(p, _like())
    assert int(tree.head_resv[3]) == 7, \
        f"kill at {stage} lost the previous in-place snapshot"
    # a clean retry commits the new state and prunes the .prev pair
    save_pytree(p, _state(8))
    assert int(restore_pytree(p, _like()).head_resv[3]) == 8
    assert not os.path.exists(str(p) + ".prev")


# ----------------------------------------------------------------------
# kill-during-save x SUPERVISED RESUME: every crash stage is followed
# by a full supervised resume that must land on the newest intact
# rotation snapshot and pass the crash-equivalence digest gate -- not
# merely restore without error (robust.supervisor;
# docs/ROBUSTNESS.md)
# ----------------------------------------------------------------------

_SUP_CACHE: dict = {}


def _supervised_job_and_ref():
    from dmclock_tpu.robust import supervisor as SV

    if "job" not in _SUP_CACHE:
        # ckpt_every=1 so the epoch-1 save always has an intact
        # epoch-0 predecessor to land on when it tears
        _SUP_CACHE["job"] = SV.EpochJob(
            engine="prefix", n=64, depth=6, ring=10, epochs=4, m=2,
            k=32, seed=13, arrival_lam=1.0, waves=2, ckpt_every=1)
        _SUP_CACHE["ref"] = SV.run_job(_SUP_CACHE["job"])
    return _SUP_CACHE["job"], _SUP_CACHE["ref"]


@pytest.mark.slow
@pytest.mark.parametrize("stage", ["data_written", "data_synced",
                                   "data_renamed", "sidecar_written",
                                   "done"])
def test_kill_during_save_then_supervised_resume(tmp_path, stage):
    """Kill inside the epoch-1 checkpoint save at every _crash_hook
    stage.  Pre-commit stages tear ckpt-00000002, so resume must land
    on the intact epoch-0 snapshot (ckpt-00000001); a kill after full
    commit ("done") must resume from the JUST-written snapshot, not
    an older one.  Either way the resumed run is bit-identical to the
    uninterrupted reference."""
    from dmclock_tpu.robust import host_faults as HF
    from dmclock_tpu.robust import supervisor as SV

    job, ref = _supervised_job_and_ref()
    plan = HF.HostFaultPlan(kill_at_save=((1, stage),))
    res = SV.run_supervised(job, tmp_path, plan)
    SV.assert_crash_equivalent(res, ref)
    assert res.restarts == 1
    want = "ckpt-00000002" if stage == "done" else "ckpt-00000001"
    assert res.resumed_from is not None and \
        res.resumed_from.endswith(want), \
        (f"kill at {stage}: resumed from {res.resumed_from}, "
         f"expected the newest intact snapshot {want}")
    # and the completed run's rotation ends on an intact final-epoch
    # snapshot a NEXT run could resume from
    payload, path = restore_pytree_rotating(
        str(tmp_path / "ckpt"), SV._payload_like(job))
    assert int(payload["epoch"]) == job.epochs
    from dmclock_tpu.utils.checkpoint import rotation_paths
    assert path == rotation_paths(tmp_path / "ckpt")[-1]


@pytest.mark.slow
def test_corrupted_newest_snapshot_supervised_resume(tmp_path):
    """The corruption-during-save fault: the epoch-1 snapshot commits
    and then rots; a later kill forces a resume that must walk PAST
    the corrupt newest entry to the intact epoch-0 one and still pass
    the digest gate."""
    from dmclock_tpu.robust import host_faults as HF
    from dmclock_tpu.robust import supervisor as SV

    job, ref = _supervised_job_and_ref()
    plan = HF.HostFaultPlan(
        corrupt_save_at=(1,),
        kill_at_decisions=(max(3 * ref.decisions // 4, 1),))
    res = SV.run_supervised(job, tmp_path, plan)
    SV.assert_crash_equivalent(res, ref)
    assert res.restarts == 1
    assert res.resumed_from is not None and \
        res.resumed_from.endswith("ckpt-00000001")


def test_double_crash_keeps_newest_committed_snapshot(tmp_path):
    """Crash AFTER full commit but before the .prev prune, then crash
    the next save mid-commit: fallback must land on the newest fully
    committed snapshot, not the stale .prev from two saves ago."""
    p = tmp_path / "snap"

    def kill_at(stage):
        def hook(s):
            if s == stage:
                raise _SimulatedKill(s)
        return hook

    save_pytree(p, _state(1))
    ckpt_mod._crash_hook = kill_at("done")     # state 2 fully commits,
    try:                                       # .prev (state 1) stays
        with pytest.raises(_SimulatedKill):
            save_pytree(p, _state(2))
    finally:
        ckpt_mod._crash_hook = None
    ckpt_mod._crash_hook = kill_at("data_renamed")   # state 3 tears
    try:
        with pytest.raises(_SimulatedKill):
            save_pytree(p, _state(3))
    finally:
        ckpt_mod._crash_hook = None
    assert int(restore_pytree(p, _like()).head_resv[3]) == 2
