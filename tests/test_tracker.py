"""Client-side ServiceTracker tests.

Scenario coverage modeled on the reference's
``test/test_dmclock_client.cc``: exact delta/rho sequences across
interleaved multi-server responses for both accounting policies, and
server-record GC with an injected clock.
"""

from dmclock_tpu.core import (BorrowingTracker, OrigTracker, Phase,
                              ServiceTracker)


def make_tracker(cls=OrigTracker, **kw):
    kw.setdefault("run_gc_thread", False)
    return ServiceTracker(tracker_cls=cls, **kw)


class TestOrigTracker:
    def test_first_contact_returns_1_1(self):
        # first request to an unknown server (reference :241-251)
        st = make_tracker()
        p = st.get_req_params("s1")
        assert (p.delta, p.rho) == (1, 1)

    def test_own_responses_excluded(self):
        # completions at the SAME server don't count toward the
        # delta/rho sent to it (reference OrigTracker::prepare_req
        # :59-67 subtracts my_delta/my_rho)
        st = make_tracker()
        st.get_req_params("s1")
        st.track_resp("s1", Phase.RESERVATION)
        p = st.get_req_params("s1")
        assert (p.delta, p.rho) == (0, 0)

    def test_cross_server_responses_counted(self):
        st = make_tracker()
        st.get_req_params("s1")  # (1,1), registers s1
        st.get_req_params("s2")  # (1,1), registers s2
        # two completions at s2: one reservation, one priority
        st.track_resp("s2", Phase.RESERVATION)
        st.track_resp("s2", Phase.PRIORITY)
        # next request to s1 reports both, rho only for the reservation
        p = st.get_req_params("s1")
        assert (p.delta, p.rho) == (2, 1)
        # and s2 excludes its own
        p = st.get_req_params("s2")
        assert (p.delta, p.rho) == (0, 0)

    def test_cost_scales_counters(self):
        st = make_tracker()
        st.get_req_params("s1")
        st.get_req_params("s2")
        st.track_resp("s2", Phase.RESERVATION, request_cost=5)
        p = st.get_req_params("s1")
        assert (p.delta, p.rho) == (5, 5)

    def test_interleaved_sequence(self):
        st = make_tracker()
        st.get_req_params("a")
        st.get_req_params("b")
        st.track_resp("a", Phase.RESERVATION)
        st.track_resp("b", Phase.PRIORITY)
        st.track_resp("a", Phase.PRIORITY)
        p = st.get_req_params("a")  # sees b's 1 completion
        assert (p.delta, p.rho) == (1, 0)
        p = st.get_req_params("b")  # sees a's 2, one reservation
        assert (p.delta, p.rho) == (2, 1)
        p = st.get_req_params("a")  # nothing new anywhere
        assert (p.delta, p.rho) == (0, 0)

    def test_response_for_unknown_server_self_heals(self):
        # response without a preceding request creates a tracker
        # (reference track_resp :227-234)
        st = make_tracker()
        st.track_resp("ghost", Phase.PRIORITY)
        assert "ghost" in st.server_map


class TestBorrowingTracker:
    def test_always_positive(self):
        st = make_tracker(BorrowingTracker)
        st.get_req_params("s1")
        for _ in range(5):
            p = st.get_req_params("s1")
            assert p.delta >= 1 and p.rho >= 1

    def test_borrow_then_repay(self):
        # reference calc_with_borrow (:110-129): with no traffic a
        # request borrows 1; a burst of completions repays the debt
        st = make_tracker(BorrowingTracker)
        st.get_req_params("s1")
        p = st.get_req_params("s1")       # borrows delta:1 rho:1
        assert (p.delta, p.rho) == (1, 1)
        tr = st.server_map["s1"]
        assert tr.delta_borrow == 1 and tr.rho_borrow == 1
        for _ in range(4):
            st.track_resp("s1", Phase.RESERVATION)
        p = st.get_req_params("s1")       # 4 new - 1 borrowed = 3
        assert (p.delta, p.rho) == (3, 3)
        assert tr.delta_borrow == 0 and tr.rho_borrow == 0

    def test_partial_repay(self):
        st = make_tracker(BorrowingTracker)
        st.get_req_params("s1")
        st.get_req_params("s1")  # borrow 1
        st.get_req_params("s1")  # borrow 2
        tr = st.server_map["s1"]
        assert tr.delta_borrow == 2
        st.track_resp("s1", Phase.PRIORITY)
        p = st.get_req_params("s1")  # 1 new <= 2 borrowed -> 1, debt 2
        assert p.delta == 1
        assert tr.delta_borrow == 2  # 2 - 1 + 1


class TestServerGc:
    def test_server_erase(self):
        # (model: reference server_erase :42-105, injected clock)
        state = {"t": 0.0}
        st = make_tracker(clean_every_s=60, clean_age_s=120,
                          monotonic_clock=lambda: state["t"])
        st.get_req_params("s1")
        st.get_req_params("s2")
        st.do_clean()  # mark (0, delta=1)
        # s2 stays active, s1 goes quiet
        state["t"] = 130.0
        st.track_resp("s2", Phase.PRIORITY)
        st.do_clean()  # erase servers with last_delta <= 1 -> s1 kept? no:
        # s1.last_delta == 1 <= earliest(1) -> erased; s2 was re-created?
        assert "s1" not in st.server_map
        # s2's tracker was created at delta=1 too; its last_delta is
        # still 1 (track_resp doesn't advance delta_prev_req), so it is
        # also erased -- matching reference get_last_delta semantics
        assert "s2" not in st.server_map
        # but the next request to s2 self-heals with fresh counters
        p = st.get_req_params("s2")
        assert (p.delta, p.rho) == (1, 1)

    def test_recent_requester_survives(self):
        state = {"t": 0.0}
        st = make_tracker(clean_every_s=60, clean_age_s=120,
                          monotonic_clock=lambda: state["t"])
        st.get_req_params("s1")
        st.get_req_params("s2")
        st.do_clean()
        state["t"] = 100.0
        st.track_resp("s1", Phase.PRIORITY)   # delta -> 2
        st.get_req_params("s1")               # s1.last_delta -> 2
        st.do_clean()                          # mark (100, 2)
        state["t"] = 130.0
        st.do_clean()  # earliest = 1 (mark at t=0); s1 at 2 survives
        assert "s1" in st.server_map
        assert "s2" not in st.server_map
