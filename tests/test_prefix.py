"""Differential tests for prefix-commit speculation.

``speculate_prefix_batch`` promises: the committed ``count`` decisions
are EXACTLY the first ``count`` decisions the serial engine
(``kernels.engine_run`` under AtLimit::Wait, fixed ``now``) would make,
and the resulting state is bit-identical to the serial engine's state
after those ``count`` decisions.  Unlike the all-or-nothing fastpath
there is no fallback: every batch commits its longest exact prefix, and
whenever the serial engine would RETURN a request the prefix is >= 1
(guaranteed progress).  These tests pin that contract on the cases the
all-or-nothing path could not handle: single-client runs, regime
transitions mid-batch, underfull candidate sets, boundary ties, and the
k-past-the-cliff shapes that used to fall off to the serial engine.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import TpuPullPriorityQueue, kernels
from dmclock_tpu.engine.fastpath import (make_prefix_runner,
                                         scan_prefix_epoch,
                                         speculate_prefix_batch)

from engine_helpers import (assert_states_equal, build_state, deep_state,
                            serial_run)

S = NS_PER_SEC


def check_prefix_vs_serial(state, now, k, *, anticipation_ns=0,
                           expect_count=None):
    """One prefix batch vs the serial engine run for `count` steps."""
    batch = speculate_prefix_batch(state, jnp.int64(now), k,
                                   anticipation_ns=anticipation_ns)
    assert bool(batch.guards_ok)
    c = int(batch.count)
    if expect_count is not None:
        assert c == expect_count, f"count {c} != expected {expect_count}"
    fd = jax.device_get(batch.decisions)
    # pad correctness
    assert (fd.slot[c:] == -1).all()
    assert (fd.type[c:] == kernels.NONE).all()
    if c == 0:
        assert_states_equal(batch.state, state)
        # nothing eligible: the serial engine must NOT return a request
        _, ser_decs = serial_run(state, now, 1)
        assert ser_decs.type[0] != kernels.RETURNING, \
            "prefix committed 0 but serial engine would serve"
        return batch.state, 0
    ser_state, ser_decs = serial_run(state, now, c)
    assert (ser_decs.type == kernels.RETURNING).all()
    assert np.array_equal(fd.slot[:c], ser_decs.slot)
    assert np.array_equal(fd.cost[:c], ser_decs.cost)
    assert np.array_equal(fd.phase[:c], ser_decs.phase)
    assert_states_equal(batch.state, ser_state)
    return batch.state, c


def drive_to_exhaustion(state, now, k, *, max_batches=200,
                        anticipation_ns=0):
    """Prefix-batch until nothing is eligible; every batch checked
    against the serial engine.  Returns the total decision count and
    the per-batch counts."""
    counts = []
    st = state
    for _ in range(max_batches):
        st, c = check_prefix_vs_serial(st, now, k,
                                       anticipation_ns=anticipation_ns)
        counts.append(c)
        if c == 0:
            break
    return st, counts


# ----------------------------------------------------------------------
# the former fallback cliffs
# ----------------------------------------------------------------------

def test_single_client_deep_queue_progresses():
    """One client with many requests: all-or-nothing speculation always
    failed here (one-serve-per-client); prefix commit must serve one
    request per batch and never stall."""
    infos = {0: ClientInfo(0, 1, 0)}
    adds = [(0, 1 * S, 1, 1, 1) for _ in range(10)]
    state = build_state(infos, adds, capacity=8)
    st, counts = drive_to_exhaustion(state, 100 * S, 8)
    assert counts[:10] == [1] * 10
    assert int(jnp.max(st.depth)) == 0


def test_underfull_commits_remaining():
    """Fewer real candidates than k: the prefix is exactly the
    remaining eligible set (the round-1 advisor's corruption shape)."""
    infos = {c: ClientInfo(0, 1, 0) for c in range(3)}
    adds = [(c, 1 * S, 1, 1, 1) for c in range(3)]
    state = build_state(infos, adds, capacity=8)
    st, c = check_prefix_vs_serial(state, 1000 * S, 8, expect_count=3)
    assert int(jnp.min(st.depth)) >= 0
    check_prefix_vs_serial(st, 1000 * S, 8, expect_count=0)


def test_regime_flip_resv_to_weight_mid_batch():
    """Reservation backlog drains mid-batch: the prefix stops exactly
    at the transition; the next batch serves the weight regime."""
    infos = {c: ClientInfo(2, 1, 0) for c in range(8)}
    state = deep_state(infos, depth=8)
    now = 4 * S
    st, counts = drive_to_exhaustion(state, now, 16, max_batches=40)
    # both regimes must have been exercised with multi-decision batches
    assert max(counts) > 1
    assert sum(counts) == 8 * 8
    assert int(jnp.max(st.depth)) == 0


def test_weight_to_resv_blocker():
    """A weight serve whose reservation tag becomes eligible (via the
    weight-debt reduction keeping resv near now) must stop the prefix
    right after it -- the serial engine switches to the constraint
    phase there."""
    # moderate reservations, now far enough that early resv tags are
    # eligible; interleaving of phases is decided by the serial engine,
    # and the prefix runner must track it exactly
    infos = {c: ClientInfo(1, 2, 0) for c in range(6)}
    state = deep_state(infos, depth=10)
    st, counts = drive_to_exhaustion(state, 3 * S, 8, max_batches=80)
    assert sum(counts) == 6 * 10
    assert int(jnp.max(st.depth)) == 0


def test_ties_at_every_boundary():
    """Equal weights + equal arrivals: every batch boundary is a pure
    creation-order tie group."""
    infos = {c: ClientInfo(0, 2, 0) for c in range(12)}
    state = deep_state(infos, depth=6)
    st = state
    total = 0
    for _ in range(10):
        st, c = check_prefix_vs_serial(st, 8 * S, 8)
        total += c
        if c == 0:
            break
    assert total == 12 * 6


def test_k_larger_than_population():
    """k far beyond the candidate count (the old k-cliff shape): the
    prefix commits what exists, repeatedly, with no cliff."""
    infos = {c: ClientInfo(0, 1 + (c % 3), 0) for c in range(8)}
    state = deep_state(infos, depth=4)
    st, counts = drive_to_exhaustion(state, 50 * S, 64, max_batches=20)
    assert sum(counts) == 8 * 4
    # with one-serve-per-client, each batch is capped at the population
    assert max(counts) <= 8


def test_limited_clients_excluded_from_weight_prefix():
    infos = {}
    for c in range(12):
        if c < 6:
            infos[c] = ClientInfo(0, 1, 0)
        else:
            infos[c] = ClientInfo(0, 1, 1000.0)
    state = deep_state(infos, depth=4)
    st = state
    for _ in range(8):
        st, c = check_prefix_vs_serial(st, 2 * S, 8)
        if c == 0:
            break


def test_nothing_eligible_commits_zero():
    infos = {c: ClientInfo(5, 0, 0) for c in range(4)}
    adds = [(c, 100 * S, 1, 1, 1) for c in range(4)]
    state = build_state(infos, adds, capacity=8)
    # now is before any reservation tag: serial returns FUTURE
    check_prefix_vs_serial(state, 1, 4, expect_count=0)


def test_empty_state_commits_zero():
    infos = {0: ClientInfo(0, 1, 0)}
    state = build_state(infos, [], capacity=8)
    check_prefix_vs_serial(state, 1 * S, 4, expect_count=0)


# ----------------------------------------------------------------------
# epoch scan
# ----------------------------------------------------------------------

def test_prefix_epoch_concatenation_is_serial_stream():
    """The concatenated per-batch prefixes of an epoch must equal one
    serial decision stream, through a workload that drains mid-epoch."""
    infos = {c: ClientInfo(0, 1 + (c % 2), 0) for c in range(8)}
    state = deep_state(infos, depth=5)       # 40 requests
    m, k = 10, 8
    ep = scan_prefix_epoch(state, jnp.int64(30 * S), m, k,
                           anticipation_ns=0)
    counts = jax.device_get(ep.count)
    assert jax.device_get(ep.guards_ok).all()
    assert int(counts.sum()) == 40
    st = state
    slots = jax.device_get(ep.slot)
    costs = jax.device_get(ep.cost)
    phases = jax.device_get(ep.phase)
    for i in range(m):
        c = int(counts[i])
        if c == 0:
            continue
        ser_state, ser_decs = serial_run(st, 30 * S, c)
        assert np.array_equal(slots[i][:c], ser_decs.slot)
        assert np.array_equal(costs[i][:c], ser_decs.cost)
        assert (ser_decs.phase == int(phases[i])).all()
        assert (slots[i][c:] == -1).all()
        st = ser_state
    assert_states_equal(ep.state, st)


def test_prefix_epoch_regime_transition():
    """An epoch spanning a resv->weight transition: batches before the
    flip are reservation-phase, after are weight-phase, stream exact."""
    infos = {c: ClientInfo(2, 1, 0) for c in range(6)}
    state = deep_state(infos, depth=12)
    m, k = 12, 8
    now = 5 * S
    ep = scan_prefix_epoch(state, jnp.int64(now), m, k,
                           anticipation_ns=0)
    counts = jax.device_get(ep.count)
    phases = jax.device_get(ep.phase)
    st = state
    for i in range(m):
        c = int(counts[i])
        if c == 0:
            continue
        ser_state, ser_decs = serial_run(st, now, c)
        assert np.array_equal(jax.device_get(ep.slot)[i][:c],
                              ser_decs.slot)
        assert (ser_decs.phase == int(phases[i])).all()
        st = ser_state
    assert_states_equal(ep.state, st)
    served_phases = {int(phases[i]) for i in range(m) if counts[i]}
    assert served_phases == {0, 1}, \
        f"epoch never crossed the transition: {served_phases}"


# ----------------------------------------------------------------------
# runner + randomized differential fuzz
# ----------------------------------------------------------------------

def test_prefix_runner_matches_serial_stream():
    infos = {c: ClientInfo(0, 1 + c % 3, 0) for c in range(10)}
    state = deep_state(infos, depth=6)
    run = make_prefix_runner(8)
    st = state
    now = 20 * S
    total = 0
    for _ in range(20):
        ser_state0 = st
        st, decs, n = run(st, jnp.int64(now))
        if n == 0:
            break
        ser_state, ser_decs = serial_run(ser_state0, now, n)
        fd = jax.device_get(decs)
        assert np.array_equal(fd.slot[:n], ser_decs.slot)
        assert_states_equal(st, ser_state)
        total += n
    assert total == 10 * 6


@pytest.mark.parametrize("seed", [31, 32, 33, 34, 35, 36])
def test_fuzz_prefix_matches_serial(seed):
    """Random QoS mixes, arrival histories, ks and nows: every batch's
    committed prefix must replay serially, bit-exact, including states
    where the old fastpath always fell back."""
    rng = random.Random(seed)
    n_clients = rng.randint(2, 24)
    infos = {}
    for c in range(n_clients):
        kind = rng.randrange(5)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 4), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4),
                                  rng.uniform(3, 8))
        elif kind == 3:
            infos[c] = ClientInfo(0, 2, 0)
        else:
            infos[c] = ClientInfo(rng.uniform(0.5, 3),
                                  rng.uniform(0.5, 3), 0)
    adds = []
    t = 1 * S
    for step in range(rng.randint(10, 150)):
        # heavy skew: some clients get long runs (the serial-ish shapes)
        c = rng.randrange(n_clients) if rng.random() < 0.7 else 0
        t += rng.randint(0, S // 4)
        delta = rng.randint(1, 5)
        adds.append((c, t, rng.randint(1, 3), delta,
                     rng.randint(1, delta)))
    state = build_state(infos, adds, capacity=32)

    k = rng.choice([2, 4, 8, 16])
    now = t + rng.randint(0, 10) * S
    st = state
    for _ in range(12):
        st, c = check_prefix_vs_serial(st, now, k)
        if c == 0:
            now += rng.randint(1, 5) * S
    assert int(jnp.min(st.depth)) >= 0


def test_fuzz_epoch_vs_batches():
    """The epoch scan must produce exactly the same stream as repeated
    single prefix batches."""
    rng = random.Random(77)
    infos = {c: ClientInfo(rng.choice([0, 1, 2]), rng.choice([1, 2, 3]),
                           0) for c in range(12)}
    for c in infos:
        if infos[c].reservation == 0 and infos[c].weight == 0:
            infos[c] = ClientInfo(0, 1, 0)
    state = deep_state(infos, depth=rng.randint(2, 8), capacity=32)
    m, k = 6, 8
    now = rng.randint(2, 500) * S
    ep = scan_prefix_epoch(state, jnp.int64(now), m, k,
                           anticipation_ns=0)
    st = state
    for i in range(m):
        batch = speculate_prefix_batch(st, jnp.int64(now), k,
                                       anticipation_ns=0)
        assert int(batch.count) == int(jax.device_get(ep.count)[i])
        assert np.array_equal(jax.device_get(batch.decisions.slot),
                              jax.device_get(ep.slot)[i])
        st = batch.state
    assert_states_equal(ep.state, st)


def test_pallas_rotate_matches_xla():
    """The Pallas ring-rotate kernel (interpret mode off-TPU) must be
    bit-identical to the XLA barrel shift for random rings/offsets."""
    from dmclock_tpu.engine.fastpath import (_rotate_rows_pallas,
                                             _rotate_rows_xla)

    rng = np.random.default_rng(9)
    for n, q, w in ((700, 16, 5), (2500, 128, 32), (100, 64, 64)):
        ring = jnp.asarray(rng.integers(-(1 << 50), 1 << 50, (n, q)),
                           jnp.int64)
        q0 = jnp.asarray(rng.integers(0, q, n), jnp.int32)
        a = _rotate_rows_xla(ring, q0, w)
        b = _rotate_rows_pallas(ring, q0, w, interpret=True)
        assert a.shape == b.shape == (w, n)
        assert (np.asarray(a) == np.asarray(b)).all(), (n, q, w)


def test_anticipation_prefix_differential():
    rng = random.Random(19)
    ant = S // 2
    infos = {c: ClientInfo(0, 1.0 + c % 3, 0) for c in range(8)}
    adds = []
    t = S
    for i in range(80):
        c = rng.randrange(8)
        t += rng.choice([ant // 4, ant // 3, 2 * ant])
        adds.append((c, t, rng.randint(1, 3), rng.randint(1, 4), 1))
    state = build_state(infos, adds, capacity=16, ring=32,
                        anticipation_ns=ant)
    now = t + 1000 * S
    st, counts = drive_to_exhaustion(state, now, 8,
                                     anticipation_ns=ant)
    assert sum(counts) == 80
    assert int(jnp.max(st.depth)) == 0
