"""Differential tests for prefix-commit speculation.

``speculate_prefix_batch`` promises: the committed ``count`` decisions
are EXACTLY the first ``count`` decisions the serial engine
(``kernels.engine_run`` under AtLimit::Wait, fixed ``now``) would make,
and the resulting state is bit-identical to the serial engine's state
after those ``count`` decisions.  Unlike the all-or-nothing fastpath
there is no fallback: every batch commits its longest exact prefix, and
whenever the serial engine would RETURN a request the prefix is >= 1
(guaranteed progress).  These tests pin that contract on the cases the
all-or-nothing path could not handle: single-client runs, regime
transitions mid-batch, underfull candidate sets, boundary ties, and the
k-past-the-cliff shapes that used to fall off to the serial engine.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo, ReqParams
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import TpuPullPriorityQueue, kernels
from dmclock_tpu.engine.fastpath import (make_prefix_runner,
                                         scan_prefix_epoch,
                                         speculate_prefix_batch)

from engine_helpers import (assert_states_equal, build_state, deep_state,
                            serial_run)

S = NS_PER_SEC


def check_prefix_vs_serial(state, now, k, *, anticipation_ns=0,
                           expect_count=None):
    """One prefix batch vs the serial engine run for `count` steps."""
    batch = speculate_prefix_batch(state, jnp.int64(now), k,
                                   anticipation_ns=anticipation_ns)
    assert bool(batch.guards_ok)
    c = int(batch.count)
    if expect_count is not None:
        assert c == expect_count, f"count {c} != expected {expect_count}"
    fd = jax.device_get(batch.decisions)
    # pad correctness
    assert (fd.slot[c:] == -1).all()
    assert (fd.type[c:] == kernels.NONE).all()
    if c == 0:
        assert_states_equal(batch.state, state)
        # nothing eligible: the serial engine must NOT return a request
        _, ser_decs = serial_run(state, now, 1)
        assert ser_decs.type[0] != kernels.RETURNING, \
            "prefix committed 0 but serial engine would serve"
        return batch.state, 0
    ser_state, ser_decs = serial_run(state, now, c)
    assert (ser_decs.type == kernels.RETURNING).all()
    assert np.array_equal(fd.slot[:c], ser_decs.slot)
    assert np.array_equal(fd.cost[:c], ser_decs.cost)
    assert np.array_equal(fd.phase[:c], ser_decs.phase)
    assert_states_equal(batch.state, ser_state)
    return batch.state, c


def drive_to_exhaustion(state, now, k, *, max_batches=200,
                        anticipation_ns=0):
    """Prefix-batch until nothing is eligible; every batch checked
    against the serial engine.  Returns the total decision count and
    the per-batch counts."""
    counts = []
    st = state
    for _ in range(max_batches):
        st, c = check_prefix_vs_serial(st, now, k,
                                       anticipation_ns=anticipation_ns)
        counts.append(c)
        if c == 0:
            break
    return st, counts


# ----------------------------------------------------------------------
# the former fallback cliffs
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_single_client_deep_queue_progresses():
    """One client with many requests: all-or-nothing speculation always
    failed here (one-serve-per-client); prefix commit must serve one
    request per batch and never stall."""
    infos = {0: ClientInfo(0, 1, 0)}
    adds = [(0, 1 * S, 1, 1, 1) for _ in range(10)]
    state = build_state(infos, adds, capacity=8)
    st, counts = drive_to_exhaustion(state, 100 * S, 8)
    assert counts[:10] == [1] * 10
    assert int(jnp.max(st.depth)) == 0


def test_underfull_commits_remaining():
    """Fewer real candidates than k: the prefix is exactly the
    remaining eligible set (the round-1 advisor's corruption shape)."""
    infos = {c: ClientInfo(0, 1, 0) for c in range(3)}
    adds = [(c, 1 * S, 1, 1, 1) for c in range(3)]
    state = build_state(infos, adds, capacity=8)
    st, c = check_prefix_vs_serial(state, 1000 * S, 8, expect_count=3)
    assert int(jnp.min(st.depth)) >= 0
    check_prefix_vs_serial(st, 1000 * S, 8, expect_count=0)


@pytest.mark.slow
def test_regime_flip_resv_to_weight_mid_batch():
    """Reservation backlog drains mid-batch: the prefix stops exactly
    at the transition; the next batch serves the weight regime."""
    infos = {c: ClientInfo(2, 1, 0) for c in range(8)}
    state = deep_state(infos, depth=8)
    now = 4 * S
    st, counts = drive_to_exhaustion(state, now, 16, max_batches=40)
    # both regimes must have been exercised with multi-decision batches
    assert max(counts) > 1
    assert sum(counts) == 8 * 8
    assert int(jnp.max(st.depth)) == 0


@pytest.mark.slow
def test_weight_to_resv_blocker():
    """A weight serve whose reservation tag becomes eligible (via the
    weight-debt reduction keeping resv near now) must stop the prefix
    right after it -- the serial engine switches to the constraint
    phase there."""
    # moderate reservations, now far enough that early resv tags are
    # eligible; interleaving of phases is decided by the serial engine,
    # and the prefix runner must track it exactly
    infos = {c: ClientInfo(1, 2, 0) for c in range(6)}
    state = deep_state(infos, depth=10)
    st, counts = drive_to_exhaustion(state, 3 * S, 8, max_batches=80)
    assert sum(counts) == 6 * 10
    assert int(jnp.max(st.depth)) == 0


@pytest.mark.slow
def test_ties_at_every_boundary():
    """Equal weights + equal arrivals: every batch boundary is a pure
    creation-order tie group."""
    infos = {c: ClientInfo(0, 2, 0) for c in range(12)}
    state = deep_state(infos, depth=6)
    st = state
    total = 0
    for _ in range(10):
        st, c = check_prefix_vs_serial(st, 8 * S, 8)
        total += c
        if c == 0:
            break
    assert total == 12 * 6


@pytest.mark.slow
def test_k_larger_than_population():
    """k far beyond the candidate count (the old k-cliff shape): the
    prefix commits what exists, repeatedly, with no cliff."""
    infos = {c: ClientInfo(0, 1 + (c % 3), 0) for c in range(8)}
    state = deep_state(infos, depth=4)
    st, counts = drive_to_exhaustion(state, 50 * S, 64, max_batches=20)
    assert sum(counts) == 8 * 4
    # with one-serve-per-client, each batch is capped at the population
    assert max(counts) <= 8


@pytest.mark.slow
def test_limited_clients_excluded_from_weight_prefix():
    infos = {}
    for c in range(12):
        if c < 6:
            infos[c] = ClientInfo(0, 1, 0)
        else:
            infos[c] = ClientInfo(0, 1, 1000.0)
    state = deep_state(infos, depth=4)
    st = state
    for _ in range(8):
        st, c = check_prefix_vs_serial(st, 2 * S, 8)
        if c == 0:
            break


def test_nothing_eligible_commits_zero():
    infos = {c: ClientInfo(5, 0, 0) for c in range(4)}
    adds = [(c, 100 * S, 1, 1, 1) for c in range(4)]
    state = build_state(infos, adds, capacity=8)
    # now is before any reservation tag: serial returns FUTURE
    check_prefix_vs_serial(state, 1, 4, expect_count=0)


def test_empty_state_commits_zero():
    infos = {0: ClientInfo(0, 1, 0)}
    state = build_state(infos, [], capacity=8)
    check_prefix_vs_serial(state, 1 * S, 4, expect_count=0)


# ----------------------------------------------------------------------
# epoch scan
# ----------------------------------------------------------------------

def test_prefix_epoch_concatenation_is_serial_stream():
    """The concatenated per-batch prefixes of an epoch must equal one
    serial decision stream, through a workload that drains mid-epoch."""
    infos = {c: ClientInfo(0, 1 + (c % 2), 0) for c in range(8)}
    state = deep_state(infos, depth=5)       # 40 requests
    m, k = 10, 8
    ep = scan_prefix_epoch(state, jnp.int64(30 * S), m, k,
                           anticipation_ns=0)
    counts = jax.device_get(ep.count)
    assert jax.device_get(ep.guards_ok).all()
    assert int(counts.sum()) == 40
    st = state
    slots = jax.device_get(ep.slot)
    costs = jax.device_get(ep.cost)
    phases = jax.device_get(ep.phase)
    for i in range(m):
        c = int(counts[i])
        if c == 0:
            continue
        ser_state, ser_decs = serial_run(st, 30 * S, c)
        assert np.array_equal(slots[i][:c], ser_decs.slot)
        assert np.array_equal(costs[i][:c], ser_decs.cost)
        assert np.array_equal(phases[i][:c], ser_decs.phase)
        assert (slots[i][c:] == -1).all()
        st = ser_state
    assert_states_equal(ep.state, st)


@pytest.mark.slow
def test_prefix_epoch_regime_transition():
    """An epoch spanning a resv->weight transition: the unified order
    commits across the boundary and the per-position phases match the
    serial engine's per-decision phase choices exactly."""
    infos = {c: ClientInfo(2, 1, 0) for c in range(6)}
    state = deep_state(infos, depth=12)
    m, k = 12, 8
    now = 5 * S
    ep = scan_prefix_epoch(state, jnp.int64(now), m, k,
                           anticipation_ns=0)
    counts = jax.device_get(ep.count)
    phases = jax.device_get(ep.phase)
    st = state
    served_phases = set()
    for i in range(m):
        c = int(counts[i])
        if c == 0:
            continue
        ser_state, ser_decs = serial_run(st, now, c)
        assert np.array_equal(jax.device_get(ep.slot)[i][:c],
                              ser_decs.slot)
        assert np.array_equal(phases[i][:c], ser_decs.phase)
        served_phases |= set(int(p) for p in phases[i][:c])
        st = ser_state
    assert_states_equal(ep.state, st)
    assert served_phases == {0, 1}, \
        f"epoch never crossed the transition: {served_phases}"


# ----------------------------------------------------------------------
# runner + randomized differential fuzz
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_prefix_runner_matches_serial_stream():
    infos = {c: ClientInfo(0, 1 + c % 3, 0) for c in range(10)}
    state = deep_state(infos, depth=6)
    run = make_prefix_runner(8)
    st = state
    now = 20 * S
    total = 0
    for _ in range(20):
        ser_state0 = st
        st, decs, n = run(st, jnp.int64(now))
        if n == 0:
            break
        ser_state, ser_decs = serial_run(ser_state0, now, n)
        fd = jax.device_get(decs)
        assert np.array_equal(fd.slot[:n], ser_decs.slot)
        assert_states_equal(st, ser_state)
        total += n
    assert total == 10 * 6


@pytest.mark.slow
@pytest.mark.parametrize("seed", [31, 32, 33, 34, 35, 36])
def test_fuzz_prefix_matches_serial(seed):
    """Random QoS mixes, arrival histories, ks and nows: every batch's
    committed prefix must replay serially, bit-exact, including states
    where the old fastpath always fell back."""
    rng = random.Random(seed)
    n_clients = rng.randint(2, 24)
    infos = {}
    for c in range(n_clients):
        kind = rng.randrange(5)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 4), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4),
                                  rng.uniform(3, 8))
        elif kind == 3:
            infos[c] = ClientInfo(0, 2, 0)
        else:
            infos[c] = ClientInfo(rng.uniform(0.5, 3),
                                  rng.uniform(0.5, 3), 0)
    adds = []
    t = 1 * S
    for step in range(rng.randint(10, 150)):
        # heavy skew: some clients get long runs (the serial-ish shapes)
        c = rng.randrange(n_clients) if rng.random() < 0.7 else 0
        t += rng.randint(0, S // 4)
        delta = rng.randint(1, 5)
        adds.append((c, t, rng.randint(1, 3), delta,
                     rng.randint(1, delta)))
    state = build_state(infos, adds, capacity=32)

    k = rng.choice([2, 4, 8, 16])
    now = t + rng.randint(0, 10) * S
    st = state
    for _ in range(12):
        st, c = check_prefix_vs_serial(st, now, k)
        if c == 0:
            now += rng.randint(1, 5) * S
    assert int(jnp.min(st.depth)) >= 0


@pytest.mark.slow
def test_fuzz_epoch_vs_batches():
    """The epoch scan must produce exactly the same stream as repeated
    single prefix batches."""
    rng = random.Random(77)
    infos = {c: ClientInfo(rng.choice([0, 1, 2]), rng.choice([1, 2, 3]),
                           0) for c in range(12)}
    for c in infos:
        if infos[c].reservation == 0 and infos[c].weight == 0:
            infos[c] = ClientInfo(0, 1, 0)
    state = deep_state(infos, depth=rng.randint(2, 8), capacity=32)
    m, k = 6, 8
    now = rng.randint(2, 500) * S
    ep = scan_prefix_epoch(state, jnp.int64(now), m, k,
                           anticipation_ns=0)
    st = state
    for i in range(m):
        batch = speculate_prefix_batch(st, jnp.int64(now), k,
                                       anticipation_ns=0)
        assert int(batch.count) == int(jax.device_get(ep.count)[i])
        assert np.array_equal(jax.device_get(batch.decisions.slot),
                              jax.device_get(ep.slot)[i])
        st = batch.state
    assert_states_equal(ep.state, st)


def test_pallas_rotate_matches_xla():
    """The Pallas ring-rotate kernel (interpret mode off-TPU) must be
    bit-identical to the XLA barrel shift for random rings/offsets."""
    from dmclock_tpu.engine.fastpath import (_rotate_rows_pallas,
                                             _rotate_rows_xla)

    rng = np.random.default_rng(9)
    for n, q, w in ((700, 16, 5), (2500, 128, 32), (100, 64, 64)):
        ring = jnp.asarray(rng.integers(-(1 << 50), 1 << 50, (n, q)),
                           jnp.int64)
        q0 = jnp.asarray(rng.integers(0, q, n), jnp.int32)
        a = _rotate_rows_xla(ring, q0, w)
        b = _rotate_rows_pallas(ring, q0, w, interpret=True)
        assert a.shape == b.shape == (w, n)
        assert (np.asarray(a) == np.asarray(b)).all(), (n, q, w)


# ----------------------------------------------------------------------
# serve chains (chain_depth > 1) + mixed-regime batches
# ----------------------------------------------------------------------

def expand_batch(batch, pre_state):
    """Flat (slots, phases, costs, lbs) stream of a ChainBatch."""
    from dmclock_tpu.engine.fastpath import expand_units

    return expand_units(jax.device_get(batch.slot),
                        jax.device_get(batch.cls),
                        jax.device_get(batch.length), pre_state,
                        limit_break=True)


def check_chain_vs_serial(state, now, k, chain_depth, *,
                          anticipation_ns=0, allow=False,
                          return_batch=False):
    """One chained batch vs the serial engine run for `count` steps."""
    from dmclock_tpu.engine.fastpath import speculate_chain_batch

    batch = speculate_chain_batch(state, jnp.int64(now), k,
                                  chain_depth=chain_depth,
                                  anticipation_ns=anticipation_ns,
                                  allow_limit_break=allow)
    assert bool(batch.guards_ok)
    c = int(batch.count)
    if c == 0:
        assert_states_equal(batch.state, state)
        _, ser_decs = serial_run_lb(state, now, 1, allow)
        assert ser_decs.type[0] != kernels.RETURNING
        return (batch.state, 0, batch) if return_batch \
            else (batch.state, 0)
    slots, phases, costs, lbs = expand_batch(batch, state)
    assert slots.shape[0] == c
    ser_state, ser_decs = serial_run_lb(state, now, c, allow)
    assert (ser_decs.type == kernels.RETURNING).all()
    assert np.array_equal(slots, ser_decs.slot)
    assert np.array_equal(phases, ser_decs.phase)
    assert np.array_equal(costs, ser_decs.cost)
    assert np.array_equal(lbs, ser_decs.limit_break)
    assert_states_equal(batch.state, ser_state)
    return (batch.state, c, batch) if return_batch \
        else (batch.state, c)


def serial_run_lb(state, now, k, allow):
    st, _, decs = kernels.engine_run(
        state, jnp.int64(now), k, allow_limit_break=allow,
        anticipation_ns=0, advance_now=False)
    return st, jax.device_get(decs)


def mixed_qos_state(n=8, depth=12, resv=2.0, seed=3):
    """Mixed-QoS population whose stream interleaves phases per
    decision -- the reference's balanced cfg4 shape and the chain
    engine's target.  The mechanism needs arrival-DOMINATED retagging:
    a weight serve advances the popped client's reservation tag by
    inv*(rho+cost) and the debt reduction subtracts exactly
    inv*(cost+rho), so prev-dominated tags are invariant under weight
    serves; only heads retagged to a recent arrival (~now) get dragged
    below now and force the constraint phase.  Arrivals therefore
    stream right up to the returned ``now``."""
    rng = random.Random(seed)
    infos = {c: ClientInfo(resv, 0.5 + (c % 4), 0) for c in range(n)}
    adds = []
    for j in range(depth):
        for c in infos:
            t = S + j * (S // 3) + rng.randint(0, S // 10)
            adds.append((c, t, 1, 1, 1))
    now = S + depth * (S // 3)
    return build_state(infos, adds, capacity=max(8, n)), now


@pytest.mark.slow
@pytest.mark.parametrize("chain_depth", [1, 2, 4])
def test_chain_balanced_mix_exact(chain_depth):
    """Balanced mixed-QoS stream (phase flips every few decisions):
    chained batches must stay bit-exact vs the serial engine, and at
    chain_depth >= 2 must commit multi-decision batches through the
    flips."""
    state, now = mixed_qos_state(n=8, depth=12)
    st = state
    total, sizes = 0, []
    for _ in range(120):
        st, c = check_chain_vs_serial(st, now, 16, chain_depth)
        sizes.append(c)
        total += c
        if c == 0:
            break
    assert total == 8 * 12
    if chain_depth >= 2:
        assert max(sizes) >= 4, \
            f"chains never amortized the phase flips: {sizes}"


def test_unified_batch_crosses_regimes():
    """ONE batch must serve both phases when reservation-eligible and
    ready-weight candidates coexist: the constraint drain and the
    weight tail commit together (the round-4 engine dispatched one
    regime per batch, so this shape always took two)."""
    infos = {}
    for c in range(3):
        # reservation-only; one eligible serve each, then the fresh
        # tag (+2s at rate 1, rho=cost=1) leaves the candidate set
        infos[c] = ClientInfo(1, 0, 0)
    for c in range(3, 6):
        infos[c] = ClientInfo(0, 2, 0)       # weight-only, ready
    state = deep_state(infos, depth=4)
    now = 2 * S
    batch = speculate_prefix_batch(state, jnp.int64(now), 32,
                                   anticipation_ns=0)
    assert bool(batch.guards_ok)
    c = int(batch.count)
    fd = jax.device_get(batch.decisions)
    phases = set(fd.phase[:c].tolist())
    assert phases == {0, 1}, \
        f"single batch served one regime only: {phases} (count {c})"
    ser_state, ser_decs = serial_run(state, now, c)
    assert np.array_equal(fd.slot[:c], ser_decs.slot)
    assert_states_equal(batch.state, ser_state)


def test_fuzz_chains_actually_fire():
    """Variable-cost workloads (offset != advance) must produce
    multi-serve chain units somewhere -- guard against the chain path
    silently never engaging."""
    from dmclock_tpu.engine.fastpath import speculate_chain_batch

    rng = random.Random(99)
    infos = {c: ClientInfo(1.0 + (c % 3), 1.0 + (c % 4), 0)
             for c in range(10)}
    adds = []
    t = 1 * S
    for _ in range(150):
        c = rng.randrange(10)
        t += rng.randint(0, S // 5)
        delta = rng.randint(1, 4)
        adds.append((c, t, rng.randint(1, 4), delta,
                     rng.randint(1, delta)))
    st = build_state(infos, adds, capacity=16, ring=64)
    now = t
    max_len = 1
    for _ in range(100):
        batch = speculate_chain_batch(st, jnp.int64(now), 10,
                                      chain_depth=4,
                                      anticipation_ns=0)
        if int(batch.count) == 0:
            now += S // 2
            continue
        max_len = max(max_len,
                      int(jax.device_get(batch.length).max()))
        st = batch.state
        if max_len > 1:
            break
    assert max_len > 1, "chains never fired on a variable-cost stream"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [41, 42, 43, 44])
def test_fuzz_chain_matches_serial(seed):
    """Random QoS mixes and chain depths: every chained batch's
    expanded stream must replay serially, bit-exact."""
    rng = random.Random(seed)
    n = rng.randint(2, 16)
    infos = {}
    for c in range(n):
        kind = rng.randrange(4)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 3), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4),
                                  rng.uniform(4, 9))
        else:
            infos[c] = ClientInfo(rng.uniform(0.5, 3),
                                  rng.uniform(0.5, 3), 0)
    adds = []
    t = 1 * S
    for _ in range(rng.randint(20, 120)):
        c = rng.randrange(n)
        t += rng.randint(0, S // 4)
        delta = rng.randint(1, 5)
        adds.append((c, t, rng.randint(1, 3), delta,
                     rng.randint(1, delta)))
    state = build_state(infos, adds, capacity=32)
    cd = rng.choice([2, 4])
    k = rng.choice([4, 8])
    now = t + rng.randint(0, 6) * S
    st = state
    for _ in range(12):
        st, c = check_chain_vs_serial(st, now, k, cd)
        if c == 0:
            now += rng.randint(1, 5) * S


def test_chain_epoch_matches_batches():
    """scan_chain_epoch must produce exactly the same unit stream as
    repeated speculate_chain_batch calls."""
    from dmclock_tpu.engine.fastpath import (scan_chain_epoch,
                                             speculate_chain_batch)

    state, now = mixed_qos_state(n=8, depth=8)
    m, k, cd = 6, 10, 3
    ep = scan_chain_epoch(state, jnp.int64(now), m, k, chain_depth=cd,
                          anticipation_ns=0)
    st = state
    for i in range(m):
        batch = speculate_chain_batch(st, jnp.int64(now), k,
                                      chain_depth=cd,
                                      anticipation_ns=0)
        assert int(batch.count) == int(jax.device_get(ep.count)[i])
        assert int(batch.unit_count) == \
            int(jax.device_get(ep.unit_count)[i])
        assert np.array_equal(jax.device_get(batch.slot),
                              jax.device_get(ep.slot)[i])
        assert np.array_equal(jax.device_get(batch.length),
                              jax.device_get(ep.length)[i])
        st = batch.state
    assert_states_equal(ep.state, st)


# ----------------------------------------------------------------------
# max_count capping (flat batches)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("cap", [0, 1, 3, 7, 20])
def test_max_count_prefix_of_prefix(cap):
    """max_count=c yields exactly the first c decisions and the same
    state as a serial run of c steps -- a shorter prefix of an exact
    prefix is still exact, including the capped promote-parity
    exclusion of the last popped head."""
    infos = {c: ClientInfo(1, 1 + c % 3, 3.0 + (c % 2)) for c in
             range(6)}
    state = deep_state(infos, depth=5)
    now = 6 * S
    full = speculate_prefix_batch(state, jnp.int64(now), 16,
                                  anticipation_ns=0)
    capped = speculate_prefix_batch(state, jnp.int64(now), 16,
                                    anticipation_ns=0, max_count=cap)
    expect = min(cap, int(full.count))
    assert int(capped.count) == expect
    fd = jax.device_get(capped.decisions)
    if expect:
        ser_state, ser_decs = serial_run(state, now, expect)
        assert np.array_equal(fd.slot[:expect], ser_decs.slot)
        assert_states_equal(capped.state, ser_state)
    else:
        assert_states_equal(capped.state, state)
    assert (fd.slot[expect:] == -1).all()


# ----------------------------------------------------------------------
# AtLimit::Allow (limit-break) on the fast path
# ----------------------------------------------------------------------

def limited_state(depth=6, n=8):
    """Everyone weight>0 with tight limits: the Allow fallback fires
    once limits are exhausted at ``now``."""
    infos = {c: ClientInfo(0.5 if c % 2 else 0, 1 + c % 3,
                           2.0 + (c % 2)) for c in range(n)}
    return deep_state(infos, depth=depth)


@pytest.mark.slow
@pytest.mark.parametrize("chain_depth", [1, 3])
def test_allow_limit_break_exact(chain_depth):
    """Allow mode: the committed stream (including limit_break flags
    and the induced constraint serves) must replay the serial engine
    under allow_limit_break=True, bit-exact, to exhaustion."""
    from dmclock_tpu.engine.fastpath import CLS_LB

    state = limited_state()
    now = 2 * S
    st = state
    total, any_lb = 0, False
    for _ in range(120):
        st, c, batch = check_chain_vs_serial(st, now, 16, chain_depth,
                                             allow=True,
                                             return_batch=True)
        if c == 0:
            break
        any_lb |= bool((jax.device_get(batch.cls)[:int(
            batch.unit_count)] >= CLS_LB).any())
        total += c
    assert total == 8 * 6, f"Allow run served {total}"
    assert int(jnp.max(st.depth)) == 0
    assert any_lb, "Allow drive never produced a limit-break unit"


def test_allow_flat_batch_flags_match_serial():
    """Flat Allow batches: limit_break flags per decision equal the
    serial engine's, and the drive reaches actual limit-breaks."""
    st = limited_state(depth=4)
    now = 3 * S
    any_lb = False
    for _ in range(40):
        batch = speculate_prefix_batch(st, jnp.int64(now), 32,
                                       anticipation_ns=0,
                                       allow_limit_break=True)
        assert bool(batch.guards_ok)
        c = int(batch.count)
        if c == 0:
            break
        ser_state, ser_decs = serial_run_lb(st, now, c, True)
        fd = jax.device_get(batch.decisions)
        assert np.array_equal(fd.slot[:c], ser_decs.slot)
        assert np.array_equal(fd.limit_break[:c], ser_decs.limit_break)
        assert np.array_equal(fd.phase[:c], ser_decs.phase)
        assert_states_equal(batch.state, ser_state)
        any_lb |= bool(fd.limit_break[:c].any())
        st = batch.state
    assert int(jnp.max(st.depth)) == 0, "Allow drive never drained"
    assert any_lb, "Allow drive never limit-broke"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [51, 52, 53])
def test_fuzz_allow_matches_serial(seed):
    """Random limited populations (weight > 0 everywhere, the Allow
    fastpath restriction): chained Allow batches replay serially."""
    rng = random.Random(seed)
    n = rng.randint(3, 12)
    infos = {c: ClientInfo(rng.choice([0, 0.5, 1.0]),
                           rng.uniform(0.5, 3),
                           rng.choice([0, 2.0, 4.0]))
             for c in range(n)}
    state = deep_state(infos, depth=rng.randint(2, 8), capacity=16)
    now = rng.randint(1, 8) * S
    st = state
    for _ in range(10):
        st, c = check_chain_vs_serial(st, now, 8,
                                      rng.choice([2, 4]),
                                      allow=True)
        if c == 0:
            now += rng.randint(1, 4) * S


@pytest.mark.slow
def test_anticipation_prefix_differential():
    rng = random.Random(19)
    ant = S // 2
    infos = {c: ClientInfo(0, 1.0 + c % 3, 0) for c in range(8)}
    adds = []
    t = S
    for i in range(80):
        c = rng.randrange(8)
        t += rng.choice([ant // 4, ant // 3, 2 * ant])
        adds.append((c, t, rng.randint(1, 3), rng.randint(1, 4), 1))
    state = build_state(infos, adds, capacity=16, ring=32,
                        anticipation_ns=ant)
    now = t + 1000 * S
    st, counts = drive_to_exhaustion(state, now, 8,
                                     anticipation_ns=ant)
    assert sum(counts) == 80
    assert int(jnp.max(st.depth)) == 0
