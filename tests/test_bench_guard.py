"""Unit tests for the drift-aware benchmark regression guard
(scripts/bench_guard.py): history medians, same-device filtering, the
tolerance floor, and the not-enough-history pass."""

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
spec = importlib.util.spec_from_file_location(
    "bench_guard", REPO / "scripts" / "bench_guard.py")
bg = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bg)


def write_history(tmp_path, rows):
    h = tmp_path / "history"
    h.mkdir()
    for i, (device, dps) in enumerate(rows):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": device,
             "workloads": {"serve": {"dps": dps}}}))
    return h


def run_guard(monkeypatch, capsys, hist, argv=()):
    monkeypatch.setattr(bg, "HISTORY", hist)
    monkeypatch.setattr(sys, "argv", ["bench_guard.py", *argv])
    rc = bg.main()
    return rc, capsys.readouterr().out


def test_no_history_passes(monkeypatch, capsys, tmp_path):
    rc, out = run_guard(monkeypatch, capsys, tmp_path / "none")
    assert rc == 0
    assert "no history" in out


def test_within_drift_passes(monkeypatch, capsys, tmp_path):
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 35e6),
                                    ("tpu0", 45e6), ("tpu0", 25e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0 and "OK" in out


def test_big_drop_fails(monkeypatch, capsys, tmp_path):
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 35e6),
                                    ("tpu0", 45e6), ("tpu0", 10e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 1 and "REGRESSION" in out


def test_device_change_not_compared(monkeypatch, capsys, tmp_path):
    # a 4x drop on a DIFFERENT device must not read as a regression
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 45e6),
                                    ("tpu1", 10e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "not judged" in out


def test_fallback_newest_annotated_not_judged(monkeypatch, capsys,
                                              tmp_path):
    # a backend-fallback (cpu) session must never read as a regression
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 45e6)])
    (hist / "bench_2000.json").write_text(json.dumps(
        {"platform": "cpu", "device": "CpuDevice(id=0)",
         "fallback": True, "backend_error": "RuntimeError: tunnel",
         "workloads": {"serve": {"dps": 0.2e6}}}))
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "backend-fallback" in out and "not judged" in out


def test_fallback_prior_excluded_from_medians(monkeypatch, capsys,
                                              tmp_path):
    # fallback records in the prior set must not drag the median down
    # and mask a real regression
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 44e6)])
    (hist / "bench_1500.json").write_text(json.dumps(
        {"platform": "cpu", "device": "tpu0", "fallback": True,
         "workloads": {"serve": {"dps": 0.2e6}}}))
    (hist / "bench_2000.json").write_text(json.dumps(
        {"platform": "tpu", "device": "tpu0",
         "workloads": {"serve": {"dps": 10e6}}}))
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 1 and "REGRESSION" in out
    assert "excluded from medians" in out


def write_history_tard(tmp_path, rows):
    """rows = [(dps, p99_tardiness_ns), ...] on one device."""
    h = tmp_path / "history"
    h.mkdir()
    for i, (dps, p99) in enumerate(rows):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"cfg4": {"dps": dps,
                                    "tardiness_p99_ns": p99}}}))
    return h


def test_tardiness_series_ok_when_stable(monkeypatch, capsys,
                                         tmp_path):
    hist = write_history_tard(tmp_path, [(40e6, 1e6), (42e6, 2e6),
                                         (41e6, 1.5e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "p99 tardiness" in out and "OK" in out


def test_tardiness_regression_warns_but_passes(monkeypatch, capsys,
                                               tmp_path):
    # tail QoS regressed 10x while throughput held: warn-only (the
    # log2 octaves and calibration shifts make a hard gate flap), and
    # the throughput verdict stays the exit code
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_tard(tmp_path,
                                           [(40e6, 1e6), (42e6, 2e6),
                                            (41e6, 15e6)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING p99 tardiness" in cap.err
    assert "tail QoS regressed" in cap.err


def test_tardiness_not_judged_without_history(monkeypatch, capsys,
                                              tmp_path):
    # records predating the telemetry plane carry no tardiness column
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 42e6)])
    (hist / "bench_2000.json").write_text(json.dumps(
        {"platform": "tpu", "device": "tpu0",
         "workloads": {"serve": {"dps": 41e6,
                                 "tardiness_p99_ns": 3e6}}}))
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "p99 tardiness" in out and "not judged" in out


def write_history_dispatch(tmp_path, rows):
    """rows = [(dps, dispatch_ms_per_launch), ...] on one device."""
    h = tmp_path / "history"
    h.mkdir()
    for i, (dps, disp) in enumerate(rows):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"cfg4": {
                 "dps": dps, "dispatch_ms_per_launch": disp}}}))
    return h


def test_dispatch_series_ok_when_stable(monkeypatch, capsys,
                                        tmp_path):
    hist = write_history_dispatch(tmp_path, [(40e6, 17.0), (42e6, 16.0),
                                             (41e6, 18.5)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "dispatch" in out and "OK" in out


def test_dispatch_regression_warns_but_passes(monkeypatch, capsys,
                                              tmp_path):
    # the per-launch dispatch tax tripled while dec/s held (the chains
    # amortize it): warn-only, throughput stays the exit code
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_dispatch(
                            tmp_path, [(40e6, 17.0), (42e6, 16.0),
                                       (41e6, 55.0)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING dispatch" in cap.err
    assert "dispatch tax regressed" in cap.err


def test_dispatch_submillisecond_median_floored(monkeypatch, capsys,
                                                tmp_path):
    # cpu boxes measure ~µs dispatch; the 1ms floor keeps jitter from
    # reading as a 2x regression
    hist = write_history_dispatch(tmp_path, [(40e6, 0.01), (42e6, 0.02),
                                             (41e6, 0.9)])
    rc, _ = run_guard(monkeypatch, capsys, hist)
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING dispatch" not in cap.err


def test_dispatch_not_judged_without_history(monkeypatch, capsys,
                                             tmp_path):
    # records predating --spans carry no dispatch column
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 42e6)])
    (hist / "bench_2000.json").write_text(json.dumps(
        {"platform": "tpu", "device": "tpu0",
         "workloads": {"serve": {"dps": 41e6,
                                 "dispatch_ms_per_launch": 17.0}}}))
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "dispatch" in out and "not judged" in out


def test_tolerance_flag(monkeypatch, capsys, tmp_path):
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 40e6),
                                    ("tpu0", 15e6)])
    rc, _ = run_guard(monkeypatch, capsys, hist)
    assert rc == 1               # 15M < 40M/2 at the default 2x
    rc2, _ = run_guard(monkeypatch, capsys, hist,
                       argv=("--tolerance", "3.0"))
    assert rc2 == 0              # 15M >= 40M/3


def write_history_rows(tmp_path, rows):
    """History records with caller-supplied workload dicts (engine_loop
    / select_impl tags included verbatim)."""
    h = tmp_path / "history"
    h.mkdir()
    for i, wl in enumerate(rows):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0", "workloads": wl}))
    return h


def test_stream_never_compared_against_round_medians(monkeypatch,
                                                     capsys,
                                                     tmp_path):
    # engine_loop splits the series even under a COLLIDING workload
    # key: a stream session's rates (one launch per chunk) must never
    # be judged against round medians -- here the stream newest is 8x
    # below the round median and must read "not judged", not
    # REGRESSION
    hist = write_history_rows(tmp_path, [
        {"cfg4": {"dps": 40e6}},
        {"cfg4": {"dps": 44e6, "engine_loop": "round"}},
        {"cfg4": {"dps": 5e6, "engine_loop": "stream"}},
    ])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "cfg4[stream]" in out and "not judged" in out


def test_stream_series_judged_against_its_own_history(monkeypatch,
                                                      capsys,
                                                      tmp_path):
    # with enough stream records the stream series is a first-class
    # regression gate of its own
    hist = write_history_rows(tmp_path, [
        {"cfg4_stream": {"dps": 80e6, "engine_loop": "stream"}},
        {"cfg4_stream": {"dps": 90e6, "engine_loop": "stream"}},
        {"cfg4_stream": {"dps": 10e6, "engine_loop": "stream"}},
    ])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 1 and "REGRESSION" in out
    assert "cfg4_stream" in out and "[stream]" not in out  # no double tag


def test_round_medians_unpolluted_by_stream_records(monkeypatch,
                                                    capsys, tmp_path):
    # two same-key stream records at 25x the round rate would lift a
    # polluted median past the newest round session's floor; the
    # engine_loop filter keeps them out, so the round session passes
    hist = write_history_rows(tmp_path, [
        {"cfg4": {"dps": 20e6}},
        {"cfg4": {"dps": 22e6, "engine_loop": "round"}},
        {"cfg4": {"dps": 500e6, "engine_loop": "stream"}},
        {"cfg4": {"dps": 500e6, "engine_loop": "stream"}},
        {"cfg4": {"dps": 12e6, "engine_loop": "round"}},
    ])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0 and "OK" in out


def test_decisions_per_launch_printed(monkeypatch, capsys, tmp_path):
    hist = write_history_rows(tmp_path, [
        {"cfg4_stream": {"dps": 80e6, "engine_loop": "stream",
                         "decisions_per_launch": 4096.0}},
        {"cfg4_stream": {"dps": 85e6, "engine_loop": "stream",
                         "decisions_per_launch": 4100.0}},
        {"cfg4_stream": {"dps": 82e6, "engine_loop": "stream",
                         "decisions_per_launch": 4098.0}},
    ])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "dec/launch" in out


# ----------------------------------------------------------------------
# churn (open-population) series -- docs/LIFECYCLE.md
# ----------------------------------------------------------------------

def _churn_row(dps, total_ids=4096, peak=4096, p99=None):
    row = {"dps": dps, "scenario": "flash_crowd",
           "total_ids": total_ids, "peak_clients": peak,
           "live_clients": peak // 2}
    if p99 is not None:
        row["tardiness_p99_ns"] = p99
    return row


def write_history_churn(tmp_path, rows):
    h = tmp_path / "history"
    h.mkdir()
    for i, row in enumerate(rows):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"churn_flash_crowd": row}}))
    return h


def test_churn_series_judged_with_population_tag(monkeypatch, capsys,
                                                 tmp_path):
    hist = write_history_churn(tmp_path, [
        _churn_row(4e6), _churn_row(5e6), _churn_row(4.5e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "churn_flash_crowd[N=4096]" in out
    assert "peak 4096 / live 2048 clients" in out
    assert "OK" in out


def test_churn_regression_fails(monkeypatch, capsys, tmp_path):
    hist = write_history_churn(tmp_path, [
        _churn_row(4e6), _churn_row(5e6), _churn_row(1e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 1 and "REGRESSION" in out


def test_churn_population_splits_the_series(monkeypatch, capsys,
                                            tmp_path):
    # a 100k-id session must NOT be median-compared against 4096-id
    # records even under the same workload key
    hist = write_history_churn(tmp_path, [
        _churn_row(40e6), _churn_row(45e6),
        _churn_row(4e6, total_ids=100_000, peak=100_000)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "not judged" in out


def test_churn_tardiness_warns_like_cfg4(monkeypatch, capsys,
                                         tmp_path):
    hist = write_history_churn(tmp_path, [
        _churn_row(4e6, p99=2e6), _churn_row(4e6, p99=2e6),
        _churn_row(4e6, p99=50e6)])
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    monkeypatch.setattr(bg, "HISTORY", hist)
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0                       # warn-only
    assert "WARNING p99 tardiness" in cap.err


def write_history_slo(tmp_path, rows):
    """rows = [(dps, violations, share_err)] -- the bench.py --slo
    scalars ride the workload row like tardiness does."""
    h = tmp_path / "history"
    h.mkdir()
    for i, (dps, viol, serr) in enumerate(rows):
        (h / f"bench_{4000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"cfg4": {
                 "dps": dps, "slo_violations_total": viol,
                 "slo_worst_share_err": serr}}}))
    return h


def test_slo_series_ok_when_stable(monkeypatch, capsys, tmp_path):
    hist = write_history_slo(tmp_path, [(40e6, 3, 0.2),
                                        (42e6, 4, 0.25),
                                        (41e6, 3, 0.22)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "slo violations" in out and "OK" in out
    assert "worst-window share err" in out


def test_slo_violation_burst_warns_but_passes(monkeypatch, capsys,
                                              tmp_path):
    # burn-rate episodes 10x the median while throughput held: the
    # QoS contract regressed -- warn-only, same policy as tardiness
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_slo(tmp_path,
                                          [(40e6, 3, 0.2),
                                           (42e6, 4, 0.2),
                                           (41e6, 40, 0.2)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING slo violations" in cap.err
    assert "burn-rate episodes up" in cap.err


def test_slo_share_err_warns_but_passes(monkeypatch, capsys,
                                        tmp_path):
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_slo(tmp_path,
                                          [(40e6, 3, 0.2),
                                           (42e6, 3, 0.25),
                                           (41e6, 3, 1.8)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING worst-window share error" in cap.err


def test_slo_clean_history_floored(monkeypatch, capsys, tmp_path):
    # a historically-clean series (median 0 violations, ~0 share err)
    # must not warn on one stray episode / 5% windowing noise
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_slo(tmp_path,
                                          [(40e6, 0, 0.0),
                                           (42e6, 0, 0.01),
                                           (41e6, 1, 0.04)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING slo" not in cap.err
    assert "WARNING worst-window" not in cap.err


def test_slo_not_judged_without_history(monkeypatch, capsys,
                                        tmp_path):
    hist = write_history_slo(tmp_path, [(40e6, 3, 0.2)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "not judged" in out


def write_history_capacity(tmp_path, rows):
    """rows = [(dps, compile_ms, retraces)] or a dict row -- the
    capacity plane's per-workload compile record (bench.py; docs/
    OBSERVABILITY.md "Capacity plane")."""
    h = tmp_path / "history"
    h.mkdir(parents=True)
    for i, row in enumerate(rows):
        if isinstance(row, tuple):
            dps, cms, rt = row
            row = {"dps": dps, "compile_ms_total": cms,
                   "retraces": rt}
        (h / f"bench_{5000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"cfg4": row}}))
    return h


def test_compile_series_ok_when_stable(monkeypatch, capsys, tmp_path):
    hist = write_history_capacity(tmp_path, [(40e6, 900.0, 0),
                                             (42e6, 1100.0, 0),
                                             (41e6, 1000.0, 0)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "compile 1000ms vs median" in out and "OK" in out
    assert "retraces 0 vs median" in out


def test_compile_blowup_warns_but_passes(monkeypatch, capsys,
                                         tmp_path):
    # a >tolerance compile-wall regression (the >15-min-Mosaic shape)
    # while dec/s held: warn-only, like the dispatch-tax series
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_capacity(tmp_path,
                                               [(40e6, 900.0, 0),
                                                (42e6, 1100.0, 0),
                                                (41e6, 9000.0, 0)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING compile" in cap.err
    assert "compile wall regressed" in cap.err


def test_retrace_churn_warns_but_passes(monkeypatch, capsys,
                                        tmp_path):
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_capacity(tmp_path,
                                               [(40e6, 900.0, 0),
                                                (42e6, 950.0, 1),
                                                (41e6, 980.0, 9)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING retraces 9" in cap.err
    assert "argument signature is churning" in cap.err


def test_compile_clean_history_floored(monkeypatch, capsys, tmp_path):
    # floors: sub-100ms compile medians and a first stray retrace are
    # cache-hit noise, not regressions -- a clean history never flaps
    monkeypatch.setattr(bg, "HISTORY",
                        write_history_capacity(tmp_path,
                                               [(40e6, 20.0, 0),
                                                (42e6, 30.0, 0),
                                                (41e6, 150.0, 1)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING compile" not in cap.err
    assert "WARNING retraces" not in cap.err


def test_capacity_skipped_rows_excluded_and_not_judged(monkeypatch,
                                                       capsys,
                                                       tmp_path):
    # a capacity-gate skip row (projected HBM over budget) neither
    # enters the medians nor gets judged as a 0-dps regression
    skip = {"dps": 0.0, "capacity_skipped": True,
            "projected_hbm_bytes": 32 << 30,
            "hbm_budget_bytes": 16 << 30}
    hist = write_history_capacity(
        tmp_path, [(40e6, 900.0, 0), (42e6, 950.0, 0), skip])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "SKIPPED by the capacity gate" in out
    # and a skip row in the PRIOR history must not drag the median
    hist2 = write_history_capacity(
        tmp_path / "h2",
        [(40e6, 900.0, 0), skip, (42e6, 950.0, 0), (41e6, 940.0, 0)])
    rc2, out2 = run_guard(monkeypatch, capsys, hist2)
    assert rc2 == 0
    assert "REGRESSION" not in out2


# ----------------------------------------------------------------------
# provenance series (margin_p99_ns / starvation_max_ns; warn-only)
# ----------------------------------------------------------------------

def write_history_prov(tmp_path, rows):
    """rows = [(dps, margin_p99_ns, starvation_max_ns, provenance_on)]
    on one device."""
    h = tmp_path / "history"
    h.mkdir()
    for i, (dps, mp99, sv, provon) in enumerate(rows):
        wl = {"dps": dps, "provenance_on": provon}
        if provon:
            wl["margin_p99_ns"] = mp99
            wl["starvation_max_ns"] = sv
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"cfg4": wl}}))
    return h


def test_prov_series_ok_when_stable(monkeypatch, capsys, tmp_path):
    hist = write_history_prov(tmp_path, [
        (40e6, 8e6, 2e8, True), (42e6, 6e6, 3e8, True),
        (41e6, 7e6, 2.5e8, True)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "margin p99" in out and "starvation max" in out
    assert "OK" in out


def test_margin_collapse_warns_but_passes(monkeypatch, capsys,
                                          tmp_path):
    # margins collapsed 10x below the median while dec/s held: the
    # proportional race tightened -- warn-only, exit 0
    monkeypatch.setattr(bg, "HISTORY", write_history_prov(
        tmp_path, [(40e6, 8e6, 1e8, True), (42e6, 10e6, 1e8, True),
                   (41e6, 0.5e6, 1e8, True)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING margin p99" in cap.err
    assert "margins collapsed" in cap.err


def test_margin_noise_floor_never_flaps(monkeypatch, capsys,
                                        tmp_path):
    # a history whose margins are already sub-ms octave noise must
    # not warn whatever the newest value does
    hist = write_history_prov(tmp_path, [
        (40e6, 0.3e6, 1e8, True), (42e6, 0.4e6, 1e8, True),
        (41e6, 0.01e6, 1e8, True)])
    monkeypatch.setattr(bg, "HISTORY", hist)
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING margin" not in cap.err


def test_starvation_growth_warns_but_passes(monkeypatch, capsys,
                                            tmp_path):
    monkeypatch.setattr(bg, "HISTORY", write_history_prov(
        tmp_path, [(40e6, 8e6, 2e8, True), (42e6, 8e6, 3e8, True),
                   (41e6, 8e6, 30e8, True)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING starvation max" in cap.err
    assert "explain.py" in cap.err


def test_starvation_floor_never_flaps(monkeypatch, capsys, tmp_path):
    # sub-100ms watermarks are one-epoch scheduling jitter: the
    # floored median (1e8) absorbs a 50x "growth" from 1ms to 150ms
    hist = write_history_prov(tmp_path, [
        (40e6, 8e6, 1e6, True), (42e6, 8e6, 2e6, True),
        (41e6, 8e6, 1.5e8, True)])
    monkeypatch.setattr(bg, "HISTORY", hist)
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING starvation" not in cap.err


def test_provenance_off_rows_split_the_series(monkeypatch, capsys,
                                              tmp_path):
    # a provenance-off session: its dps never enters the on-series
    # medians, its tag prints [prov-off], and on-rows' provenance
    # scalars never compare against it (it has none)
    hist = write_history_prov(tmp_path, [
        (40e6, 8e6, 1e8, True), (42e6, 8e6, 1e8, True),
        (10e6, 0, 0, False)])   # 4x "drop" -- but a DIFFERENT series
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "[prov-off]" in out
    assert "not judged" in out


def test_provenance_on_medians_unpolluted_by_off_rows(monkeypatch,
                                                      capsys,
                                                      tmp_path):
    # two off-rows at 10x the rate must not raise the on-series
    # median past the newest on-row's floor
    hist = write_history_prov(tmp_path, [
        (400e6, 8e6, 1e8, False), (400e6, 8e6, 1e8, False),
        (40e6, 8e6, 1e8, True), (42e6, 8e6, 1e8, True),
        (41e6, 8e6, 1e8, True)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "REGRESSION" not in out


# -- mesh serving plane series (bench.py --mode mesh) -----------------

def _mesh_row(dps, *, shards=8, sync=1, per_shard=None):
    per = per_shard if per_shard is not None else dps / shards
    return {"dps": dps, "engine_loop": "mesh", "n_shards": shards,
            "counter_sync_every": sync, "dps_per_shard_mean": per,
            "clients_total": 100_000,
            "clients_per_shard": 100_000 // shards}


def write_history_mesh(tmp_path, rows):
    h = tmp_path / "history"
    h.mkdir()
    for i, row in enumerate(rows):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"mesh": row}}))
    return h


def test_mesh_series_judged_with_shard_tag(monkeypatch, capsys,
                                           tmp_path):
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6), _mesh_row(90e6), _mesh_row(85e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "mesh[S=8,K=1,N=100000,P=static]" in out
    assert "/shard aggregate-of-8" in out
    assert "OK" in out


def test_mesh_regression_fails(monkeypatch, capsys, tmp_path):
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6), _mesh_row(90e6), _mesh_row(20e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 1 and "REGRESSION" in out


def test_mesh_shard_count_splits_the_series(monkeypatch, capsys,
                                            tmp_path):
    # an 8-shard aggregate must NOT be median-compared against
    # 1-shard records even under the same workload key
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6, shards=8), _mesh_row(90e6, shards=8),
        _mesh_row(11e6, shards=1)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "mesh[S=1,K=1,N=100000,P=static]" in out
    assert "not judged" in out


def test_mesh_sync_cadence_splits_the_series(monkeypatch, capsys,
                                             tmp_path):
    # K=4 sessions exchange 4x fewer counters -- a different machine,
    # never compared against K=1 records in either direction
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6, sync=1), _mesh_row(90e6, sync=1),
        _mesh_row(20e6, sync=4)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "mesh[S=8,K=4,N=100000,P=static]" in out
    assert "not judged" in out


def test_mesh_per_shard_collapse_warns_but_passes(monkeypatch,
                                                  capsys, tmp_path):
    # aggregate holds (more shards papering over a slower engine) but
    # per-shard dec/s collapsed: warn-only, never a hard failure
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6, per_shard=10e6),
        _mesh_row(88e6, per_shard=11e6),
        _mesh_row(80e6, per_shard=2e6)])
    monkeypatch.setattr(bg, "HISTORY", hist)
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING per-shard" in cap.err
    assert "REGRESSION" not in cap.out


def test_mesh_per_shard_stable_ok(monkeypatch, capsys, tmp_path):
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6), _mesh_row(88e6), _mesh_row(84e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "per-shard 10.50M vs median" in out


def test_mesh_client_population_splits_the_series(monkeypatch,
                                                  capsys, tmp_path):
    # a 1M-client session legitimately runs slower per aggregate
    # (per-epoch work grows with N, decisions stay bounded by m*k) --
    # it must NOT be median-compared against 100k-client records
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6), _mesh_row(90e6),
        dict(_mesh_row(8e6), clients_total=1_000_000,
             clients_per_shard=125_000)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "mesh[S=8,K=1,N=1000000,P=static]" in out
    assert "not judged" in out


# -- chaos (fault-bearing) mesh rows (bench.py --fault-plan <spec>) ---

def _chaos_mesh_row(dps, **over):
    row = _mesh_row(dps)
    row.update({"fault_plan": "T32xS8:drop12+resync11+inject138",
                "fault_dropouts_per_shard": [2] * 8,
                "fault_resyncs_per_shard": [1] * 8}, **over)
    return row


def test_chaos_mesh_row_not_judged(monkeypatch, capsys, tmp_path):
    # the newest row bears a fault plan: its rate reflects injected
    # dropouts, not the engine -- announced, never judged, rc 0 even
    # though the rate cratered
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6), _mesh_row(90e6), _chaos_mesh_row(5e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "chaos (fault-injection) row" in out
    assert "REGRESSION" not in out


def test_chaos_mesh_rows_excluded_from_medians(monkeypatch, capsys,
                                               tmp_path):
    # two prior chaos rows at 1/10th the clean rate must not drag the
    # clean median under the newest clean row's floor
    hist = write_history_mesh(tmp_path, [
        _chaos_mesh_row(8e6), _chaos_mesh_row(9e6),
        _mesh_row(80e6), _mesh_row(90e6), _mesh_row(84e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "REGRESSION" not in out
    assert "vs median 85.0M over 2 sessions" in out


def test_chaos_mesh_medians_unpolluted_upward(monkeypatch, capsys,
                                              tmp_path):
    # the mirror direction: a chaos row at 10x must not RAISE the
    # clean median and fail an honest clean session
    hist = write_history_mesh(tmp_path, [
        _chaos_mesh_row(900e6), _chaos_mesh_row(950e6),
        _mesh_row(80e6), _mesh_row(90e6), _mesh_row(84e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "REGRESSION" not in out


def test_chaos_row_prints_dropout_accounting(monkeypatch, capsys,
                                             tmp_path):
    hist = write_history_mesh(tmp_path, [
        _mesh_row(80e6), _mesh_row(90e6), _chaos_mesh_row(40e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "fault_plan 'T32xS8:drop12+resync11+inject138'" in out
    assert "dropouts [2, 2, 2, 2, 2, 2, 2, 2]" in out


# -- controller A/B sessions (bench.py --mode controller) -------------

def _ctl_row(dps, *, decisions=2, sides="both"):
    return {"workload": "controller", "dps": dps,
            "scenario": "shard_skew", "total_ids": 192,
            "engine_loop": "stream", "controller": sides,
            "controller_decisions": decisions,
            "recovered_dps": 1e4, "burn_epochs_on": 8,
            "burn_epochs_off": 20}


def _ctl_rec(row, **extra):
    return {"platform": "tpu", "device": "tpu0",
            "controller": row.get("controller", "both"),
            "workloads": {"controller_shard_skew": row}, **extra}


def test_controller_actuated_newest_not_judged(monkeypatch, capsys,
                                               tmp_path):
    # the newest session's controller actually actuated: its on-twin
    # wall time includes knob transitions + recompiles -- announced,
    # never judged, rc 0 even though the rate cratered
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 45e6)])
    (hist / "bench_2000.json").write_text(json.dumps(
        _ctl_rec(_ctl_row(2e6, decisions=3))))
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "controller-actuated session" in out
    assert "3 journaled decision(s)" in out
    assert "REGRESSION" not in out


def test_controller_actuated_priors_excluded_from_medians(
        monkeypatch, capsys, tmp_path):
    # actuated records in the prior set must not drag the clean
    # median down and mask a real regression on a bare session
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 44e6)])
    (hist / "bench_1500.json").write_text(json.dumps(
        _ctl_rec(_ctl_row(0.2e6))))
    (hist / "bench_2000.json").write_text(json.dumps(
        {"platform": "tpu", "device": "tpu0",
         "workloads": {"serve": {"dps": 10e6}}}))
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 1 and "REGRESSION" in out
    assert "controller-actuated record(s)" in out


def test_controller_zero_decisions_is_clean_and_tagged(monkeypatch,
                                                       capsys,
                                                       tmp_path):
    # a controller session that never actuated IS a clean run (the
    # digest gate pins it bit-identical to the bare runner): judged
    # against its own ctl-tagged series, actuation count printed
    h = tmp_path / "history"
    h.mkdir()
    for i, dps in enumerate((30e6, 34e6, 31e6)):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            _ctl_rec(_ctl_row(dps, decisions=0))))
    rc, out = run_guard(monkeypatch, capsys, h)
    assert rc == 0
    assert "controller_shard_skew[stream][N=192][ctl=both]" in out
    assert "0 controller actuation(s)" in out
    assert "OK" in out


def test_controller_tag_splits_the_series(monkeypatch, capsys,
                                          tmp_path):
    # zero-actuation controller rows at 10x the bare rate must not
    # RAISE the bare serve median and fail an honest clean session
    # (record-level exclusion does not bite at zero decisions, so
    # the row-level series identity is what protects the medians)
    hist = write_history(tmp_path, [("tpu0", 40e6), ("tpu0", 44e6)])
    for ts, dps in ((1500, 400e6), (1501, 420e6)):
        (hist / f"bench_{ts}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "controller": "both",
             "workloads": {"serve": {
                 "dps": dps, "controller": "both",
                 "controller_decisions": 0}}}))
    (hist / "bench_2000.json").write_text(json.dumps(
        {"platform": "tpu", "device": "tpu0",
         "workloads": {"serve": {"dps": 35e6}}}))
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "REGRESSION" not in out
    assert "vs median 42.0M over 2 sessions" in out


# -- rpc ingest plane sessions (bench.py --mode rpc; docs/RPC.md) -----

def _rpc_row(dps, *, workers=4, scenario="none", drops=0,
             lat99=20.0, digest_match=True):
    return {"workload": "rpc", "dps": dps, "scenario": scenario,
            "workers": workers, "requests_per_worker": 64,
            "ingest_drops": drops, "lat_p99_ms": lat99,
            "lat_p50_ms": lat99 / 4, "digest_match": digest_match,
            "chaos_exact": True}


def write_history_rpc(tmp_path, rows):
    h = tmp_path / "history"
    h.mkdir()
    for i, row in enumerate(rows):
        (h / f"bench_{1000 + i}.json").write_text(json.dumps(
            {"platform": "tpu", "device": "tpu0",
             "workloads": {"rpc": row}}))
    return h


def test_rpc_series_judged_with_scenario_worker_tag(monkeypatch,
                                                    capsys, tmp_path):
    hist = write_history_rpc(tmp_path, [
        _rpc_row(4e6), _rpc_row(5e6), _rpc_row(4.5e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "rpc[none,W=4]" in out
    assert "OK" in out


def test_rpc_regression_fails(monkeypatch, capsys, tmp_path):
    hist = write_history_rpc(tmp_path, [
        _rpc_row(4e6), _rpc_row(5e6), _rpc_row(1e6)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 1 and "REGRESSION" in out


def test_rpc_worker_count_splits_the_series(monkeypatch, capsys,
                                            tmp_path):
    # an 8-worker session drives different arrival concurrency than a
    # 4-worker one -- never median-compared even under the same key
    hist = write_history_rpc(tmp_path, [
        _rpc_row(40e6), _rpc_row(45e6), _rpc_row(4e6, workers=8)])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "rpc[none,W=8]" in out and "not judged" in out


def test_rpc_rows_never_pollute_non_rpc_medians(monkeypatch, capsys,
                                                tmp_path):
    # the workers key joins the series identity from BOTH sides: two
    # rpc-shaped rows under a colliding workload key must not drag a
    # bare workload's median
    hist = write_history_rows(tmp_path, [
        {"serve": {"dps": 40e6}},
        {"serve": {"dps": 44e6}},
        {"serve": _rpc_row(1e6)},
        {"serve": _rpc_row(1.2e6)},
        {"serve": {"dps": 38e6}},
    ])
    rc, out = run_guard(monkeypatch, capsys, hist)
    assert rc == 0
    assert "REGRESSION" not in out
    assert "vs median 42.0M over 2 sessions" in out


def test_rpc_ingest_drop_growth_warns_but_passes(monkeypatch, capsys,
                                                 tmp_path):
    # device clamp discards 5x past the floored median while dec/s
    # held: warn-only -- drop counts ride arrival timing over real
    # sockets, a hard gate would flap
    monkeypatch.setattr(bg, "HISTORY", write_history_rpc(
        tmp_path, [_rpc_row(4e6, drops=0), _rpc_row(4.2e6, drops=0),
                   _rpc_row(4.1e6, drops=5)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING ingest drops" in cap.err
    assert "overrunning wave capacity" in cap.err


def test_rpc_lat_p99_growth_warns_but_passes(monkeypatch, capsys,
                                             tmp_path):
    monkeypatch.setattr(bg, "HISTORY", write_history_rpc(
        tmp_path, [_rpc_row(4e6, lat99=60.0),
                   _rpc_row(4.2e6, lat99=70.0),
                   _rpc_row(4.1e6, lat99=400.0)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING admit->commit p99" in cap.err
    assert "end-to-end tail regressed" in cap.err


def test_rpc_clean_history_floors_never_flap(monkeypatch, capsys,
                                             tmp_path):
    # a clean-drop history (median 0, floored at 1) must not warn on
    # one stray clamp, and sub-50ms p99 medians must not warn on
    # wall-clock jitter under the 50ms floor
    monkeypatch.setattr(bg, "HISTORY", write_history_rpc(
        tmp_path, [_rpc_row(4e6, drops=0, lat99=10.0),
                   _rpc_row(4.2e6, drops=0, lat99=15.0),
                   _rpc_row(4.1e6, drops=1, lat99=90.0)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING ingest drops" not in cap.err
    assert "WARNING admit->commit" not in cap.err


def test_rpc_digest_mismatch_warns(monkeypatch, capsys, tmp_path):
    # the bench's own digest gate (live vs journaled-trace replay)
    # failed: surfaced loudly on stderr even though throughput held
    monkeypatch.setattr(bg, "HISTORY", write_history_rpc(
        tmp_path, [_rpc_row(4e6), _rpc_row(4.2e6),
                   _rpc_row(4.1e6, digest_match=False)]))
    monkeypatch.setattr(sys, "argv", ["bench_guard.py"])
    rc = bg.main()
    cap = capsys.readouterr()
    assert rc == 0
    assert "WARNING rpc digest MISMATCH" in cap.err
    assert "not crash-equivalent" in cap.err
