"""Cross-language simulator parity: native dmc_sim vs Python dmc_sim.

The native simulator (native/sim/) replicates the Python discrete-event
harness including CPython-compatible MT19937 server selection
(native/sim/pymt19937.h), so for the same config+seed the full service
trace -- (virtual ns, server, client, phase, cost) per op -- must be
BIT-IDENTICAL across languages.  This is the strongest cross-language
gate: it transitively pins the native scheduler, tracker, harness, and
config parser against their Python counterparts.
"""

import subprocess
from pathlib import Path

import pytest

from dmclock_tpu.sim.config import parse_config_file
from dmclock_tpu.sim.dmc_sim import run_sim

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "native" / "build"


@pytest.fixture(scope="module")
def dmc_sim_native():
    exe = BUILD / "dmc_sim_native"
    if not exe.exists():
        import shutil
        if not shutil.which("cmake"):
            pytest.skip("no cmake; native sim unavailable")
        subprocess.run(["cmake", "-S", str(REPO / "native"), "-B",
                        str(BUILD)], check=True, capture_output=True)
        subprocess.run(["cmake", "--build", str(BUILD), "-j", "--target",
                        "dmc_sim_native"], check=True,
                       capture_output=True)
    return exe


def native_trace(exe, conf, model, seed, server_mode="pull"):
    out = subprocess.run(
        [str(exe), "-c", str(conf), "--model", model, "--seed",
         str(seed), "--server-mode", server_mode, "--trace"],
        check=True, capture_output=True, text=True, timeout=300).stdout
    trace = []
    report = []
    for line in out.splitlines():
        if line.startswith("TRACE "):
            t, srv, cli, phase, cost = line.split()[1:]
            trace.append((int(t), int(srv), int(cli), int(phase),
                          int(cost)))
        else:
            report.append(line)
    return trace, "\n".join(report)


@pytest.mark.parametrize("conf,py_model,native_model,seed", [
    ("configs/dmc_sim_example.conf", "dmclock", "dmclock", 12345),
    ("configs/dmc_sim_example.conf", "dmclock-delayed", "dmclock-delayed",
     12345),
    ("configs/dmc_sim_100th.conf", "dmclock", "dmclock", 12345),
    ("configs/dmc_sim_100th.conf", "dmclock", "dmclock", 999),
    ("configs/dmc_sim_example.conf", "ssched", "ssched", 12345),
])
def test_trace_parity_native_vs_python(dmc_sim_native, conf, py_model,
                                       native_model, seed):
    cfg = parse_config_file(str(REPO / conf))
    py = run_sim(cfg, model=py_model, seed=seed, record_trace=True)
    py_trace = [(t, s, c, p, co) for (t, s, c, p, co) in py.trace]
    nat_trace, _ = native_trace(dmc_sim_native, REPO / conf,
                                native_model, seed)
    assert len(py_trace) == len(nat_trace) > 0
    for i, (a, b) in enumerate(zip(py_trace, nat_trace)):
        assert a == b, f"trace diverges at op {i}: py={a} native={b}"


@pytest.mark.parametrize("model", ["dmclock", "dmclock-delayed",
                                   "ssched"])
def test_push_trace_parity_native_vs_python(dmc_sim_native, model):
    """Push-driven servers, cross-language: python --server-mode push
    and native --server-mode push must produce the same bit-identical
    trace as each other (and as pull mode, pinned separately)."""
    conf = "configs/dmc_sim_example.conf"
    cfg = parse_config_file(str(REPO / conf))
    py = run_sim(cfg, model=model, seed=7, record_trace=True,
                 server_mode="push")
    py_trace = [(t, s, c, p, co) for (t, s, c, p, co) in py.trace]
    nat_trace, _ = native_trace(dmc_sim_native, REPO / conf, model, 7,
                                server_mode="push")
    assert len(py_trace) == len(nat_trace) > 0
    for i, (a, b) in enumerate(zip(py_trace, nat_trace)):
        assert a == b, f"trace diverges at op {i}: py={a} native={b}"


def test_push_trace_parity_multithread(dmc_sim_native, tmp_path):
    """threads > 1: push pacing may legitimately diverge from pull, but
    the python and native PUSH sims must still agree bit for bit."""
    conf = tmp_path / "mt.conf"
    conf.write_text("""\
[global]
server_groups = 1
client_groups = 1
server_random_selection = false
server_soft_limit = false

[server.0]
server_count = 2
server_iops = 160
server_threads = 3

[client.0]
client_count = 4
client_wait = 0
client_total_ops = 400
client_server_select_range = 2
client_iops_goal = 200
client_outstanding_ops = 16
client_reservation = 10.0
client_limit = 0.0
client_weight = 1.0
""")
    cfg = parse_config_file(str(conf))
    py = run_sim(cfg, model="dmclock-delayed", seed=5,
                 record_trace=True, server_mode="push")
    py_trace = [(t, s, c, p, co) for (t, s, c, p, co) in py.trace]
    nat_trace, _ = native_trace(dmc_sim_native, conf,
                                "dmclock-delayed", 5,
                                server_mode="push")
    assert len(py_trace) == len(nat_trace) > 0
    for i, (a, b) in enumerate(zip(py_trace, nat_trace)):
        assert a == b, f"trace diverges at op {i}: py={a} native={b}"


def test_native_report_totals(dmc_sim_native):
    _, report = native_trace(dmc_sim_native,
                             REPO / "configs/dmc_sim_100th.conf",
                             "dmclock", 12345)
    assert "total ops: 100000" in report
    assert "clients: 100  servers: 100" in report


def test_ssched_sim_native_runs():
    exe = BUILD / "ssched_sim_native"
    if not exe.exists():
        pytest.skip("ssched_sim_native not built")
    out = subprocess.run(
        [str(exe), "-c", str(REPO / "configs/dmc_sim_example.conf")],
        check=True, capture_output=True, text=True, timeout=120).stdout
    assert "total ops: 8000" in out
