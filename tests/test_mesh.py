"""Mesh serving plane (parallel.mesh / parallel.cluster mesh rounds /
robust.guarded.run_mesh_chunk_guarded / robust.supervisor
``engine_loop="mesh"`` / bench shard planning).

The headline gates:

- **S=1 identity**: a 1-shard mesh job's decision digest, final
  state, and metric totals are BIT-IDENTICAL to the round AND stream
  loops on all three epoch engines (the per-shard program IS the
  stream chunk's own epoch step -- ``engine.stream.make_epoch_step``
  -- so this is a construction, re-pinned here);
- **crash equivalence**: a mesh run SIGKILLed at any host-fault point
  and resumed produces the same everything, counter plane included;
- **counter plane**: per-shard delta/rho completion counters fold the
  SLO window's exact delivered columns, views refresh only on the
  ``counter_sync_every`` grid and stay monotone;
- **window merge**: per-shard SLO blocks merged IN-GRAPH through
  ``window_mesh_reduce`` equal the host combine, and publish with a
  ``shard`` label (the churn-free merge gate).

The S-shard-vs-host-loop cluster digest gate lives in
``tests/test_cluster_realism.py`` next to the other cluster parity
gates."""

import dataclasses

import jax
import numpy as np
import pytest

from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.obs import slo as obsslo
from dmclock_tpu.parallel import mesh as M
from dmclock_tpu.parallel import tracker as TRK
from dmclock_tpu.robust import host_faults as HF
from dmclock_tpu.robust import supervisor as SV

BASE = dict(n=96, depth=6, ring=10, epochs=5, m=2, seed=5,
            arrival_lam=1.0, waves=2, ckpt_every=2)
JOBS = {
    "prefix-sort": SV.EpochJob(engine="prefix", k=16,
                               select_impl="sort", **BASE),
    "prefix-radix": SV.EpochJob(engine="prefix", k=16,
                                select_impl="radix", **BASE),
    "chain": SV.EpochJob(engine="chain", chain_depth=3, k=8, **BASE),
    "calendar-minstop": SV.EpochJob(engine="calendar", k=4,
                                    calendar_impl="minstop", **BASE),
    "calendar-bucketed": SV.EpochJob(engine="calendar", k=4,
                                     calendar_impl="bucketed",
                                     ladder_levels=2, **BASE),
}

_REFS: dict = {}


def mesh_job(name: str, n_shards: int = 1, **over) -> SV.EpochJob:
    return dataclasses.replace(JOBS[name], engine_loop="mesh",
                               n_shards=n_shards, **over)


def ref_of(name: str, loop: str) -> SV.SupervisedResult:
    key = (name, loop)
    if key not in _REFS:
        _REFS[key] = SV.run_job(
            dataclasses.replace(JOBS[name], engine_loop=loop))
    return _REFS[key]


def assert_core_equal(a: SV.SupervisedResult,
                      b: SV.SupervisedResult) -> None:
    assert a.digest == b.digest, "decision digest diverged"
    assert a.state_digest == b.state_digest, "final state diverged"
    assert a.decisions == b.decisions
    assert np.array_equal(np.asarray(a.metrics),
                          np.asarray(b.metrics))


class TestMeshIdentityGate:
    # one engine per family stays in the quick sweep (the tier-1
    # budget discipline); the remaining fast paths are slow-marked
    # and run by scripts/run_tests.sh + the ci.sh mesh smoke
    @pytest.mark.parametrize("name", [
        "prefix-sort", "chain", "calendar-minstop",
        pytest.param("prefix-radix", marks=pytest.mark.slow),
        pytest.param("calendar-bucketed", marks=pytest.mark.slow),
    ])
    def test_s1_mesh_bit_identical_to_round_and_stream(self, name):
        """The acceptance gate: S=1 engine_loop="mesh" == "round" ==
        "stream" (digest + final state + metrics) on all three
        engines."""
        m = SV.run_job(mesh_job(name))
        assert m.decisions > 0
        assert_core_equal(m, ref_of(name, "round"))
        assert_core_equal(m, ref_of(name, "stream"))
        assert m.mesh_counters is not None
        assert m.mesh_counters.shape == (2, 1, JOBS[name].n)
        assert m.mesh_fallbacks == 0

    @pytest.mark.slow
    def test_s1_telemetry_planes_bit_identical(self):
        """hists + ledger + SLO window/ring/episodes + provenance all
        ride the mesh carry and must equal the stream loop's blocks
        exactly (the planes-ride-for-free contract)."""
        tele = dict(with_hists=True, with_ledger=True, with_slo=True,
                    with_prov=True)
        s = SV.run_job(dataclasses.replace(
            JOBS["prefix-sort"], engine_loop="stream", **tele))
        m = SV.run_job(mesh_job("prefix-sort", **tele))
        assert_core_equal(m, s)
        for f in ("hists", "ledger", "slo_window", "slo_ring",
                  "slo_cepoch", "prov_margin_hist", "prov_scal",
                  "prov_last_served"):
            assert np.array_equal(np.asarray(getattr(m, f)),
                                  np.asarray(getattr(s, f))), f
        assert m.slo == s.slo

    def test_no_ingest_mesh(self):
        """arrival_lam=0 runs serve-only mesh chunks."""
        m = SV.run_job(mesh_job("prefix-sort", arrival_lam=0.0))
        r = SV.run_job(dataclasses.replace(
            JOBS["prefix-sort"], engine_loop="round",
            arrival_lam=0.0))
        assert_core_equal(m, r)

    def test_mesh_rejects_churn_and_flight(self):
        from dmclock_tpu.lifecycle import churn as churn_mod

        spec = churn_mod.make_spec("flash_crowd", total_ids=32)
        with pytest.raises(ValueError, match="churn"):
            SV.run_job(mesh_job("prefix-sort", churn=spec))
        with pytest.raises(ValueError, match="flight"):
            SV.run_job(mesh_job("prefix-sort", flight_records=8))

    def test_mesh_rejects_oversubscribed_shards(self):
        with pytest.raises(ValueError, match="devices"):
            SV.run_job(mesh_job("prefix-sort",
                                n_shards=len(jax.devices()) + 1))


class TestMeshScaling:
    def test_s4_aggregate_scales_and_counters_account(self):
        """4 shards serve ~4x the decisions of 1 shard (saturated
        closed-loop shape), and the counter plane accounts every
        completion: cd == the per-shard delivered totals."""
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True)
        m4 = SV.run_job(job)
        m1 = SV.run_job(mesh_job("prefix-sort", with_slo=True))
        assert m4.decisions > 2.5 * m1.decisions
        cd = m4.mesh_counters[0]
        assert cd.shape == (4, JOBS["prefix-sort"].n)
        assert int(cd.sum()) == m4.decisions
        # every shard holds the SAME view (same psum, same sync grid)
        vd = m4.mesh_views[0]
        assert (vd == vd[0]).all()
        assert (vd >= 1).all()

    def test_counter_sync_grid_staleness(self):
        """K=5 with a 5-epoch run syncs ONLY at epoch 0 (where the
        counters are still the protocol origin): the final held view
        stays at 1 everywhere while K=1's view saw every boundary --
        the staleness knob is real, and the decisions/counters are
        untouched by it (views never feed this workload's ingest
        params; the cluster-model gate where they DO feed decisions
        lives in test_cluster_realism)."""
        m1 = SV.run_job(mesh_job("prefix-sort", n_shards=2,
                                 counter_sync_every=1))
        m5 = SV.run_job(mesh_job("prefix-sort", n_shards=2,
                                 counter_sync_every=5))
        assert m1.digest == m5.digest
        assert np.array_equal(m1.mesh_counters, m5.mesh_counters)
        v1, v5 = m1.mesh_views[0], m5.mesh_views[0]
        assert (v5 == 1).all()
        assert (v5 <= v1).all()
        assert (v1 > 1).any()

    def test_exchange_schedule_accounting(self):
        sched = TRK.exchange_schedule(12, 4)
        assert sched["syncs"] == 3
        assert sched["sync_frac"] == 0.25
        assert TRK.exchange_schedule(5, 1)["syncs"] == 5
        assert TRK.counter_view_bytes(1000) == 16_000
        # an off-grid window start (the bench's post-warmup timed
        # window): global epochs [8, 32) at K=7 sync at 14/21/28 only
        assert TRK.exchange_schedule(24, 7, start=8)["syncs"] == 3
        # a window starting ON the grid counts its first epoch
        assert TRK.exchange_schedule(8, 4, start=8)["syncs"] == 2
        # brute-force oracle across offsets and cadences
        for start in range(0, 9):
            for every in (1, 2, 3, 5, 7):
                for n in (0, 1, 6, 13):
                    want = sum(1 for e in range(start, start + n)
                               if e % every == 0)
                    got = TRK.exchange_schedule(n, every,
                                                start=start)["syncs"]
                    assert got == want, (start, every, n)


class TestMeshWindowMerge:
    def test_in_graph_merge_equals_host_combine(self):
        """The satellite gate: per-shard window blocks merged through
        window_mesh_reduce (in-graph, inside the mesh chunk) == the
        host-side window_combine_np over the fetched shards --
        churn-free closed population, every column."""
        import jax.numpy as jnp

        job = mesh_job("prefix-sort", n_shards=4)
        mesh = M.make_mesh(4)
        state = M.stack_shards(
            SV._job_state(dataclasses.replace(
                JOBS["prefix-sort"], engine_loop="stream")), 4, mesh)
        cd, cr, vd, vr = M.counter_init(4, job.n)
        slo0 = M.stack_shards(obsslo.window_zero(job.n), 4, mesh)
        fn = M.jit_mesh_chunk(mesh, engine="prefix", epochs=3,
                              m=job.m, k=job.k,
                              dt_epoch_ns=job.dt_epoch_ns,
                              waves=job.waves, with_metrics=True,
                              counter_sync_every=1, ingest=True)
        rng = np.random.Generator(np.random.PCG64(9))
        counts = rng.poisson(1.0, (4, 3, job.n)).astype(np.int32)
        out = fn(state, cd, cr, vd, vr, jnp.int64(0),
                 jnp.asarray(counts), None, None, slo0, None)
        host = obsslo.window_combine_np(
            np.zeros((job.n, obsslo.W_FIELDS), np.int64),
            *np.asarray(jax.device_get(out.slo)))
        assert np.array_equal(host,
                              np.asarray(jax.device_get(
                                  out.slo_merged)))
        assert int(host[:, obsslo.W_OPS].sum()) > 0

    def test_publish_shard_windows_labels(self):
        from dmclock_tpu.obs.registry import MetricsRegistry

        reg = MetricsRegistry()
        blocks = np.zeros((2, 4, obsslo.W_FIELDS), np.int64)
        blocks[0, :, obsslo.W_OPS] = 3
        blocks[1, :, obsslo.W_OPS] = 5
        obsslo.publish_shard_windows(reg, blocks)
        text = reg.prometheus()
        assert 'dmclock_slo_window_ops{shard="0"} 12' in text
        assert 'dmclock_slo_window_ops{shard="1"} 20' in text
        assert 'dmclock_slo_window_ops{shard="all"} 32' in text

    def test_mesh_slo_rolls_cluster_wide_table(self):
        """A with_slo mesh run rolls ONE cluster-wide merged window
        per boundary: delivered ops in the judged ring equal the sum
        across shards (not one shard's slice)."""
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True)
        m = SV.run_job(job)
        ring = np.asarray(m.slo_ring)
        assert ring.shape[0] > 0
        ops_col = 5  # seq, cid, cepoch, e0, e1, ops, ...
        total_ring_ops = int(ring[:, ops_col].sum())
        # every delivered decision lands in exactly one closed window
        assert total_ring_ops == m.decisions


class TestMeshFallback:
    def test_tag32_trip_falls_back_bit_identical(self):
        """A tag32 window trip anywhere in the mesh chunk discards it
        and replays epoch-major on the round path -- bit-identical to
        the stream loop's own fallback at S=1, and counted."""
        trip = dict(tag_width=32, tag_spread_ns=1 << 33)
        s = SV.run_job(dataclasses.replace(
            JOBS["prefix-sort"], engine_loop="stream", **trip))
        m = SV.run_job(mesh_job("prefix-sort", **trip))
        assert_core_equal(m, s)
        assert m.mesh_fallbacks > 0

    @pytest.mark.slow
    def test_s2_fallback_deterministic(self):
        """S=2 with a trip: the epoch-major host replay is
        deterministic -- two runs agree on everything."""
        trip = dict(tag_width=32, tag_spread_ns=1 << 33)
        a = SV.run_job(mesh_job("prefix-sort", n_shards=2, **trip))
        b = SV.run_job(mesh_job("prefix-sort", n_shards=2, **trip))
        assert a.mesh_fallbacks > 0
        assert_core_equal(a, b)
        assert np.array_equal(a.mesh_counters, b.mesh_counters)
        assert np.array_equal(a.mesh_views, b.mesh_views)


class TestMeshCrashEquivalence:
    def test_zero_host_fault_gate(self, tmp_path):
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True)
        ref = SV.run_job(job)
        sup = SV.run_supervised(job, tmp_path / "wd",
                                HF.zero_host_plan())
        SV.assert_crash_equivalent(sup, ref)
        assert sup.restarts == 0

    @pytest.mark.parametrize("frac", [0.35, 0.75])
    def test_sigkill_mid_mesh_resumes_bit_identical(self, tmp_path,
                                                    frac):
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True,
                       with_hists=True, with_ledger=True)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(int(ref.decisions * frac),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)

    @pytest.mark.slow
    def test_spawn_sigkill_mid_mesh(self, tmp_path):
        """Spawn mode: a REAL SIGKILL in a child interpreter, plus
        the result-file JSON round-trip of the mesh fields
        (counters/views/fallbacks)."""
        job = mesh_job("prefix-sort", n_shards=2, with_slo=True)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(int(ref.decisions * 0.5),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan,
                                mode="spawn")
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)
        assert sup.mesh_counters is not None
        assert np.array_equal(sup.mesh_views, ref.mesh_views)

    @pytest.mark.slow
    def test_kill_during_save_resumes(self, tmp_path):
        job = mesh_job("chain", n_shards=2)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(kill_at_save=((1, "data_written"),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)


class TestShardPlanning:
    def test_plan_capacity_inverts_the_client_target(self,
                                                     monkeypatch):
        """The shard count FALLS OUT of the client target: with a
        budget that fits ~B clients/shard, planning N clients yields
        ceil(N / max_clients) shards (capped at the device count)."""
        import bench

        from dmclock_tpu.obs import capacity as obscap

        budget = obscap.projected_hbm(
            4096, ring=10, engine="prefix", m=2, k=16,
            telemetry=True, slo=True, stream_chunk=8)
        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES",
                           str(int(budget / 0.9) + 1))
        plan = bench.plan_mesh_shards(8192, None, ring=10,
                                      engine="prefix", m=2, k=16,
                                      stream_chunk=8)
        assert plan["shards_planned"] >= 2
        assert plan["max_clients_per_shard"] <= 4096 + 64
        assert plan["n_shards"] <= len(jax.devices())
        assert plan["clients_per_shard"] * plan["n_shards"] >= 8192
        assert plan["projected_hbm_bytes_per_shard"] > 0

    def test_no_budget_falls_back_to_device_count(self, monkeypatch):
        import bench

        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES", "0")
        plan = bench.plan_mesh_shards(1000, None, ring=10,
                                      engine="prefix", m=2, k=16)
        assert plan["shards_planned"] is None
        assert plan["n_shards"] == len(jax.devices())

    def test_explicit_shards_capped_at_devices(self, monkeypatch):
        import bench

        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES", "0")
        plan = bench.plan_mesh_shards(
            1000, len(jax.devices()) + 7, ring=10, engine="prefix",
            m=2, k=16)
        assert plan["n_shards"] == len(jax.devices())


class TestMeshRoundsComposition:
    def test_chunked_launches_compose(self):
        """Two fused cluster-mesh launches of E/2 rounds each, with
        views/metrics threaded through, == one launch of E rounds."""
        import jax.numpy as jnp

        from dmclock_tpu.core import ClientInfo
        from dmclock_tpu.parallel import cluster as CL
        from dmclock_tpu.robust import cluster as RC

        S, C, E, k = 4, 10, 6, 12
        mesh = CL.make_mesh(S)
        infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0)
                 for c in range(C)]

        def fresh():
            cl = CL.init_cluster(S, C)
            cl = CL.install_clients(
                cl,
                jnp.asarray([i.reservation_inv_ns for i in infos],
                            jnp.int64),
                jnp.asarray([i.weight_inv_ns for i in infos],
                            jnp.int64),
                jnp.asarray([i.limit_inv_ns for i in infos],
                            jnp.int64))
            return CL.shard_cluster(cl, mesh)

        rng = np.random.Generator(np.random.PCG64(7))
        arrivals = rng.integers(0, 3, size=(E, S, C)).astype(np.int32)
        # K=2 with an ODD chunk split: the second launch starts at
        # global round 3, so its sync grid must come from round0
        # (local indexing would sync at 3, 5 instead of 4) -- the
        # chunked digest only matches the single launch if the grid
        # is global
        for K in (1, 2):
            vd, vr = CL.init_mesh_views(S, C)
            met = jnp.zeros((S, obsdev.NUM_METRICS), jnp.int64)
            cl = fresh()
            digs = []
            r0 = 0
            for half in (arrivals[:3], arrivals[3:]):
                out = CL.run_mesh_rounds(
                    cl, half, 1, mesh, decisions_per_step=k,
                    max_arrivals=2, advance_ns=10 ** 8,
                    counter_sync_every=K, round0=r0,
                    view_delta=vd, view_rho=vr, metrics=met)
                cl, vd, vr, met = (out.cluster, out.view_delta,
                                   out.view_rho, out.metrics)
                digs.extend(CL.mesh_decs_seq(out.decs))
                r0 += half.shape[0]
            one = CL.run_mesh_rounds(
                fresh(), arrivals, 1, mesh, decisions_per_step=k,
                max_arrivals=2, advance_ns=10 ** 8,
                counter_sync_every=K)
            assert RC.decision_digest(digs) == \
                RC.decision_digest(CL.mesh_decs_seq(one.decs)), \
                f"K={K} chunked composition diverged"
            assert np.array_equal(np.asarray(met),
                                  np.asarray(one.metrics))
            assert np.array_equal(np.asarray(vd),
                                  np.asarray(one.view_delta))


class TestMultichipRecordV2:
    """MULTICHIP record schema v2 (scripts/run_fullscale.py): the
    reader accepts v1 rounds (no schema key, no mesh block) and v2
    records carrying the mesh throughput trajectory."""

    @staticmethod
    def _load_reader():
        import importlib.util
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location(
            "run_fullscale", repo / "scripts" / "run_fullscale.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_reader_accepts_v1(self, tmp_path):
        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text('{"n_devices": 8, "rc": 0, "ok": true, '
                     '"skipped": false, "tail": "dryrun ok"}')
        rec = mod.load_multichip(str(p))
        assert rec["schema"] == 1
        assert rec["mesh"] is None
        assert rec["ok"] and rec["n_devices"] == 8
        assert rec["tail"] == "dryrun ok"

    def test_reader_accepts_real_v1_rounds(self):
        """Every recorded MULTICHIP_r* round must keep loading."""
        import glob
        from pathlib import Path

        mod = self._load_reader()
        repo = Path(__file__).resolve().parent.parent
        rounds = sorted(glob.glob(str(repo / "MULTICHIP_r0*.json")))
        assert rounds, "expected recorded MULTICHIP rounds"
        for p in rounds:
            rec = mod.load_multichip(p)
            assert rec["schema"] == 1
            assert rec["n_devices"] >= 1

    def test_reader_accepts_v2(self, tmp_path):
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text(_json.dumps({
            "schema": 2, "n_devices": 8, "rc": 0, "ok": True,
            "skipped": False, "tail": "dryrun ok",
            "mesh": {"dps": 1.5e6, "dps_per_shard_mean": 2e5,
                     "n_shards": 8, "counter_sync_every": 2,
                     "counter_bytes_per_epoch": 100000,
                     "clients_total": 100000}}))
        rec = mod.load_multichip(str(p))
        assert rec["schema"] == 2
        assert rec["mesh"]["dps"] == 1.5e6
        assert rec["mesh"]["counter_sync_every"] == 2

    def test_v2_mesh_defaults_normalized(self, tmp_path):
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text(_json.dumps({
            "schema": 2, "n_devices": 4, "rc": 0, "ok": True,
            "tail": "", "mesh": {"dps": 5.0}}))
        rec = mod.load_multichip(str(p))
        assert rec["mesh"]["n_shards"] == 4
        assert rec["mesh"]["counter_sync_every"] == 1
        assert rec["mesh"]["counter_bytes_per_epoch"] == 0
