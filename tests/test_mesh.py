"""Mesh serving plane (parallel.mesh / parallel.cluster mesh rounds /
robust.guarded.run_mesh_chunk_guarded / robust.supervisor
``engine_loop="mesh"`` / bench shard planning).

The headline gates:

- **S=1 identity**: a 1-shard mesh job's decision digest, final
  state, and metric totals are BIT-IDENTICAL to the round AND stream
  loops on all three epoch engines (the per-shard program IS the
  stream chunk's own epoch step -- ``engine.stream.make_epoch_step``
  -- so this is a construction, re-pinned here);
- **crash equivalence**: a mesh run SIGKILLed at any host-fault point
  and resumed produces the same everything, counter plane included;
- **counter plane**: per-shard delta/rho completion counters fold the
  SLO window's exact delivered columns, views refresh only on the
  ``counter_sync_every`` grid and stay monotone;
- **window merge**: per-shard SLO blocks merged IN-GRAPH through
  ``window_mesh_reduce`` equal the host combine, and publish with a
  ``shard`` label (the churn-free merge gate).

The S-shard-vs-host-loop cluster digest gate lives in
``tests/test_cluster_realism.py`` next to the other cluster parity
gates."""

import dataclasses

import jax
import numpy as np
import pytest

from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.obs import slo as obsslo
from dmclock_tpu.parallel import mesh as M
from dmclock_tpu.parallel import tracker as TRK
from dmclock_tpu.robust import host_faults as HF
from dmclock_tpu.robust import supervisor as SV

BASE = dict(n=96, depth=6, ring=10, epochs=5, m=2, seed=5,
            arrival_lam=1.0, waves=2, ckpt_every=2)
JOBS = {
    "prefix-sort": SV.EpochJob(engine="prefix", k=16,
                               select_impl="sort", **BASE),
    "prefix-radix": SV.EpochJob(engine="prefix", k=16,
                                select_impl="radix", **BASE),
    "chain": SV.EpochJob(engine="chain", chain_depth=3, k=8, **BASE),
    "calendar-minstop": SV.EpochJob(engine="calendar", k=4,
                                    calendar_impl="minstop", **BASE),
    "calendar-bucketed": SV.EpochJob(engine="calendar", k=4,
                                     calendar_impl="bucketed",
                                     ladder_levels=2, **BASE),
    "calendar-wheel": SV.EpochJob(engine="calendar", k=4,
                                  calendar_impl="wheel",
                                  ladder_levels=2, **BASE),
}

_REFS: dict = {}


def mesh_job(name: str, n_shards: int = 1, **over) -> SV.EpochJob:
    return dataclasses.replace(JOBS[name], engine_loop="mesh",
                               n_shards=n_shards, **over)


def ref_of(name: str, loop: str) -> SV.SupervisedResult:
    key = (name, loop)
    if key not in _REFS:
        _REFS[key] = SV.run_job(
            dataclasses.replace(JOBS[name], engine_loop=loop))
    return _REFS[key]


def assert_core_equal(a: SV.SupervisedResult,
                      b: SV.SupervisedResult) -> None:
    assert a.digest == b.digest, "decision digest diverged"
    assert a.state_digest == b.state_digest, "final state diverged"
    assert a.decisions == b.decisions
    assert np.array_equal(np.asarray(a.metrics),
                          np.asarray(b.metrics))


class TestMeshIdentityGate:
    # one engine per family stays in the quick sweep (the tier-1
    # budget discipline); the remaining fast paths are slow-marked
    # and run by scripts/run_tests.sh + the ci.sh mesh smoke
    @pytest.mark.parametrize("name", [
        "prefix-sort", "chain", "calendar-minstop",
        pytest.param("prefix-radix", marks=pytest.mark.slow),
        pytest.param("calendar-bucketed", marks=pytest.mark.slow),
        pytest.param("calendar-wheel", marks=pytest.mark.slow),
    ])
    def test_s1_mesh_bit_identical_to_round_and_stream(self, name):
        """The acceptance gate: S=1 engine_loop="mesh" == "round" ==
        "stream" (digest + final state + metrics) on all three
        engines."""
        m = SV.run_job(mesh_job(name))
        assert m.decisions > 0
        assert_core_equal(m, ref_of(name, "round"))
        assert_core_equal(m, ref_of(name, "stream"))
        assert m.mesh_counters is not None
        assert m.mesh_counters.shape == (2, 1, JOBS[name].n)
        assert m.mesh_fallbacks == 0

    @pytest.mark.slow
    def test_s1_telemetry_planes_bit_identical(self):
        """hists + ledger + SLO window/ring/episodes + provenance all
        ride the mesh carry and must equal the stream loop's blocks
        exactly (the planes-ride-for-free contract)."""
        tele = dict(with_hists=True, with_ledger=True, with_slo=True,
                    with_prov=True)
        s = SV.run_job(dataclasses.replace(
            JOBS["prefix-sort"], engine_loop="stream", **tele))
        m = SV.run_job(mesh_job("prefix-sort", **tele))
        assert_core_equal(m, s)
        for f in ("hists", "ledger", "slo_window", "slo_ring",
                  "slo_cepoch", "prov_margin_hist", "prov_scal",
                  "prov_last_served"):
            assert np.array_equal(np.asarray(getattr(m, f)),
                                  np.asarray(getattr(s, f))), f
        assert m.slo == s.slo

    def test_no_ingest_mesh(self):
        """arrival_lam=0 runs serve-only mesh chunks."""
        m = SV.run_job(mesh_job("prefix-sort", arrival_lam=0.0))
        r = SV.run_job(dataclasses.replace(
            JOBS["prefix-sort"], engine_loop="round",
            arrival_lam=0.0))
        assert_core_equal(m, r)

    def test_mesh_composition_rejections(self):
        """What mesh still rejects up front (each with a reasoned
        message): churn+slo (slot-indexed merge), churn+fault_plan
        (dead-shard boundary semantics), fault_plan off-mesh, and an
        unparseable fault spec.  Plain churn and flight_records now
        COMPOSE (TestMeshChurn / TestMeshFlight)."""
        from dmclock_tpu.lifecycle import churn as churn_mod

        spec = churn_mod.make_spec("flash_crowd", total_ids=32)
        with pytest.raises(ValueError, match="with_slo"):
            SV.run_job(mesh_job("prefix-sort", churn=spec,
                                with_slo=True))
        with pytest.raises(ValueError, match="fault_plan"):
            SV.run_job(mesh_job("prefix-sort", churn=spec,
                                fault_plan={"seed": 1}))
        with pytest.raises(ValueError, match="mesh"):
            SV.run_job(dataclasses.replace(
                JOBS["prefix-sort"], engine_loop="stream",
                fault_plan={"seed": 1}))
        with pytest.raises(ValueError, match="spec"):
            SV.run_job(mesh_job("prefix-sort",
                                fault_plan={"bogus_key": 1}))
        # a plain LABEL cannot seed a plan -- rejected, not silently
        # run benign; the bench's spec-STRING form is accepted
        with pytest.raises(ValueError, match="did not parse"):
            SV.run_job(mesh_job("prefix-sort",
                                fault_plan="chaos-label"))
        # a shard_skew spec built for a different shard count would
        # silently smear the melt across shards -- rejected
        skew = churn_mod.make_spec("shard_skew", total_ids=32,
                                   n_shards=4)
        with pytest.raises(ValueError, match="shard_skew"):
            SV.run_job(mesh_job("prefix-sort", n_shards=2,
                                churn=skew))

    def test_mesh_rejects_oversubscribed_shards(self):
        with pytest.raises(ValueError, match="devices"):
            SV.run_job(mesh_job("prefix-sort",
                                n_shards=len(jax.devices()) + 1))


class TestMeshScaling:
    def test_s4_aggregate_scales_and_counters_account(self):
        """4 shards serve ~4x the decisions of 1 shard (saturated
        closed-loop shape), and the counter plane accounts every
        completion: cd == the per-shard delivered totals."""
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True)
        m4 = SV.run_job(job)
        m1 = SV.run_job(mesh_job("prefix-sort", with_slo=True))
        assert m4.decisions > 2.5 * m1.decisions
        cd = m4.mesh_counters[0]
        assert cd.shape == (4, JOBS["prefix-sort"].n)
        assert int(cd.sum()) == m4.decisions
        # every shard holds the SAME view (same psum, same sync grid)
        vd = m4.mesh_views[0]
        assert (vd == vd[0]).all()
        assert (vd >= 1).all()

    def test_counter_sync_grid_staleness(self):
        """K=5 with a 5-epoch run syncs ONLY at epoch 0 (where the
        counters are still the protocol origin): the final held view
        stays at 1 everywhere while K=1's view saw every boundary --
        the staleness knob is real, and the decisions/counters are
        untouched by it (views never feed this workload's ingest
        params; the cluster-model gate where they DO feed decisions
        lives in test_cluster_realism)."""
        m1 = SV.run_job(mesh_job("prefix-sort", n_shards=2,
                                 counter_sync_every=1))
        m5 = SV.run_job(mesh_job("prefix-sort", n_shards=2,
                                 counter_sync_every=5))
        assert m1.digest == m5.digest
        assert np.array_equal(m1.mesh_counters, m5.mesh_counters)
        v1, v5 = m1.mesh_views[0], m5.mesh_views[0]
        assert (v5 == 1).all()
        assert (v5 <= v1).all()
        assert (v1 > 1).any()

    def test_exchange_schedule_accounting(self):
        sched = TRK.exchange_schedule(12, 4)
        assert sched["syncs"] == 3
        assert sched["sync_frac"] == 0.25
        assert TRK.exchange_schedule(5, 1)["syncs"] == 5
        assert TRK.counter_view_bytes(1000) == 16_000
        # an off-grid window start (the bench's post-warmup timed
        # window): global epochs [8, 32) at K=7 sync at 14/21/28 only
        assert TRK.exchange_schedule(24, 7, start=8)["syncs"] == 3
        # a window starting ON the grid counts its first epoch
        assert TRK.exchange_schedule(8, 4, start=8)["syncs"] == 2
        # brute-force oracle across offsets and cadences
        for start in range(0, 9):
            for every in (1, 2, 3, 5, 7):
                for n in (0, 1, 6, 13):
                    want = sum(1 for e in range(start, start + n)
                               if e % every == 0)
                    got = TRK.exchange_schedule(n, every,
                                                start=start)["syncs"]
                    assert got == want, (start, every, n)


def _collective_execs(jaxpr, mult=1):
    """EXECUTED collective count: walk the jaxpr multiplying by scan
    trip counts.  Counting "all-reduce" in compiled HLO TEXT is
    constant across K -- lax.scan traces its body once -- so text
    counting cannot distinguish a per-epoch psum from a per-group
    one; this walk counts what the program runs, not what it
    contains."""
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if "psum" in name or "pmax" in name or "all_reduce" in name:
            total += mult
            continue
        m2 = mult
        if name == "scan":
            m2 = mult * eqn.params["length"]
        for v in eqn.params.values():
            if isinstance(v, jax.extend.core.ClosedJaxpr):
                total += _collective_execs(v.jaxpr, m2)
            elif hasattr(v, "eqns"):
                total += _collective_execs(v, m2)
    return total


class TestCollectiveSkipping:
    """Non-sync epochs execute ZERO collectives, by program
    structure: the chunk scan regrouped into epochs/K sync groups
    pays ONE counter psum per group head and must stay bit-identical
    to the flat per-epoch program whenever the chunk starts on the
    sync grid."""

    def _chunk_fn(self, S, E, K, skipping):
        import jax.numpy as jnp

        mesh = M.make_mesh(S)
        job = JOBS["prefix-sort"]
        state = M.stack_shards(
            SV._job_state(dataclasses.replace(
                job, engine_loop="stream")), S, mesh)
        cd, cr, vd, vr = M.counter_init(S, job.n)
        slo0 = M.stack_shards(obsslo.window_zero(job.n), S, mesh)
        fn = M.jit_mesh_chunk(mesh, engine="prefix", epochs=E,
                              m=job.m, k=job.k,
                              dt_epoch_ns=job.dt_epoch_ns,
                              waves=job.waves, with_metrics=True,
                              counter_sync_every=K, ingest=True,
                              collective_skipping=skipping)
        rng = np.random.Generator(np.random.PCG64(13))
        counts = jnp.asarray(
            rng.poisson(1.0, (S, E, job.n)).astype(np.int32))
        args = (state, cd, cr, vd, vr, jnp.int64(0), counts,
                None, None, slo0, None)
        return fn, args

    def test_grouped_bit_identical_to_flat(self):
        """K=2 over 4 epochs, grouped vs flat, aligned chunk: every
        output leaf bitwise equal (states, outs, counters, views,
        merged SLO block)."""
        fn_g, args = self._chunk_fn(2, 4, 2, True)
        fn_f, _ = self._chunk_fn(2, 4, 2, False)
        out_g = fn_g(*args)
        out_f = fn_f(*args)
        leaves_g = jax.tree.leaves(out_g)
        leaves_f = jax.tree.leaves(out_f)
        assert len(leaves_g) == len(leaves_f)
        for a, b in zip(leaves_g, leaves_f):
            assert np.array_equal(np.asarray(jax.device_get(a)),
                                  np.asarray(jax.device_get(b)))

    def test_collective_execution_counts(self):
        """The structural gate: flat executes 2E+2 collectives (cd/cr
        psum per epoch + the final window-merge psum/pmax); grouped
        executes 2*(E/K)+2 -- and the a1-a8 identity
        flat - grouped(K=E) == (E-1) * (grouped(K=E/2) - grouped(K=E))
        pins that the difference is exactly the per-epoch pair."""
        E = 8
        counts = {}
        for K, skip in ((1, False), (4, True), (8, True)):
            fn, args = self._chunk_fn(2, E, K, skip)
            jx = jax.make_jaxpr(fn)(*args)
            counts[K] = _collective_execs(jx.jaxpr)
        assert counts[1] == 2 * E + 2, counts
        assert counts[4] == 2 * (E // 4) + 2, counts
        assert counts[8] == 2 * (E // 8) + 2, counts
        assert counts[1] - counts[8] == \
            (E - 1) * (counts[4] - counts[8])

    def test_supervised_grouped_digest_equals_flat(self):
        """Supervisor-level: a K=2 mesh job whose chunks align with
        the sync grid runs the grouped program (auto-resolved in
        run_mesh_chunk_guarded) and must equal K=1 bit for bit."""
        k2 = SV.run_job(mesh_job("prefix-sort", n_shards=2, epochs=4,
                                 ckpt_every=2, counter_sync_every=2))
        k1 = SV.run_job(mesh_job("prefix-sort", n_shards=2, epochs=4,
                                 ckpt_every=2, counter_sync_every=1))
        assert k2.digest == k1.digest
        assert k2.state_digest == k1.state_digest
        assert np.array_equal(k2.mesh_counters, k1.mesh_counters)


class TestMeshWindowMerge:
    def test_in_graph_merge_equals_host_combine(self):
        """The satellite gate: per-shard window blocks merged through
        window_mesh_reduce (in-graph, inside the mesh chunk) == the
        host-side window_combine_np over the fetched shards --
        churn-free closed population, every column."""
        import jax.numpy as jnp

        job = mesh_job("prefix-sort", n_shards=4)
        mesh = M.make_mesh(4)
        state = M.stack_shards(
            SV._job_state(dataclasses.replace(
                JOBS["prefix-sort"], engine_loop="stream")), 4, mesh)
        cd, cr, vd, vr = M.counter_init(4, job.n)
        slo0 = M.stack_shards(obsslo.window_zero(job.n), 4, mesh)
        fn = M.jit_mesh_chunk(mesh, engine="prefix", epochs=3,
                              m=job.m, k=job.k,
                              dt_epoch_ns=job.dt_epoch_ns,
                              waves=job.waves, with_metrics=True,
                              counter_sync_every=1, ingest=True)
        rng = np.random.Generator(np.random.PCG64(9))
        counts = rng.poisson(1.0, (4, 3, job.n)).astype(np.int32)
        out = fn(state, cd, cr, vd, vr, jnp.int64(0),
                 jnp.asarray(counts), None, None, slo0, None)
        host = obsslo.window_combine_np(
            np.zeros((job.n, obsslo.W_FIELDS), np.int64),
            *np.asarray(jax.device_get(out.slo)))
        assert np.array_equal(host,
                              np.asarray(jax.device_get(
                                  out.slo_merged)))
        assert int(host[:, obsslo.W_OPS].sum()) > 0

    def test_publish_shard_windows_labels(self):
        from dmclock_tpu.obs.registry import MetricsRegistry

        reg = MetricsRegistry()
        blocks = np.zeros((2, 4, obsslo.W_FIELDS), np.int64)
        blocks[0, :, obsslo.W_OPS] = 3
        blocks[1, :, obsslo.W_OPS] = 5
        obsslo.publish_shard_windows(reg, blocks)
        text = reg.prometheus()
        assert 'dmclock_slo_window_ops{shard="0"} 12' in text
        assert 'dmclock_slo_window_ops{shard="1"} 20' in text
        assert 'dmclock_slo_window_ops{shard="all"} 32' in text

    def test_mesh_slo_rolls_cluster_wide_table(self):
        """A with_slo mesh run rolls ONE cluster-wide merged window
        per boundary: delivered ops in the judged ring equal the sum
        across shards (not one shard's slice)."""
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True)
        m = SV.run_job(job)
        ring = np.asarray(m.slo_ring)
        assert ring.shape[0] > 0
        ops_col = 5  # seq, cid, cepoch, e0, e1, ops, ...
        total_ring_ops = int(ring[:, ops_col].sum())
        # every delivered decision lands in exactly one closed window
        assert total_ring_ops == m.decisions


class TestMeshFallback:
    def test_tag32_trip_falls_back_bit_identical(self):
        """A tag32 window trip anywhere in the mesh chunk discards it
        and replays epoch-major on the round path -- bit-identical to
        the stream loop's own fallback at S=1, and counted."""
        trip = dict(tag_width=32, tag_spread_ns=1 << 33)
        s = SV.run_job(dataclasses.replace(
            JOBS["prefix-sort"], engine_loop="stream", **trip))
        m = SV.run_job(mesh_job("prefix-sort", **trip))
        assert_core_equal(m, s)
        assert m.mesh_fallbacks > 0

    @pytest.mark.slow
    def test_s2_fallback_deterministic(self):
        """S=2 with a trip: the epoch-major host replay is
        deterministic -- two runs agree on everything."""
        trip = dict(tag_width=32, tag_spread_ns=1 << 33)
        a = SV.run_job(mesh_job("prefix-sort", n_shards=2, **trip))
        b = SV.run_job(mesh_job("prefix-sort", n_shards=2, **trip))
        assert a.mesh_fallbacks > 0
        assert_core_equal(a, b)
        assert np.array_equal(a.mesh_counters, b.mesh_counters)
        assert np.array_equal(a.mesh_views, b.mesh_views)


class TestMeshCrashEquivalence:
    def test_zero_host_fault_gate(self, tmp_path):
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True)
        ref = SV.run_job(job)
        sup = SV.run_supervised(job, tmp_path / "wd",
                                HF.zero_host_plan())
        SV.assert_crash_equivalent(sup, ref)
        assert sup.restarts == 0

    @pytest.mark.parametrize("frac", [0.35, 0.75])
    def test_sigkill_mid_mesh_resumes_bit_identical(self, tmp_path,
                                                    frac):
        job = mesh_job("prefix-sort", n_shards=4, with_slo=True,
                       with_hists=True, with_ledger=True)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(int(ref.decisions * frac),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)

    @pytest.mark.slow
    def test_spawn_sigkill_mid_mesh(self, tmp_path):
        """Spawn mode: a REAL SIGKILL in a child interpreter, plus
        the result-file JSON round-trip of the mesh fields
        (counters/views/fallbacks)."""
        job = mesh_job("prefix-sort", n_shards=2, with_slo=True)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(int(ref.decisions * 0.5),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan,
                                mode="spawn")
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)
        assert sup.mesh_counters is not None
        assert np.array_equal(sup.mesh_views, ref.mesh_views)

    @pytest.mark.slow
    def test_kill_during_save_resumes(self, tmp_path):
        job = mesh_job("chain", n_shards=2)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(kill_at_save=((1, "data_written"),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)


class TestShardPlanning:
    def test_plan_capacity_inverts_the_client_target(self,
                                                     monkeypatch):
        """The shard count FALLS OUT of the client target: with a
        budget that fits ~B clients/shard, planning N clients yields
        ceil(N / max_clients) shards (capped at the device count)."""
        import bench

        from dmclock_tpu.obs import capacity as obscap

        budget = obscap.projected_hbm(
            4096, ring=10, engine="prefix", m=2, k=16,
            telemetry=True, slo=True, stream_chunk=8)
        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES",
                           str(int(budget / 0.9) + 1))
        plan = bench.plan_mesh_shards(8192, None, ring=10,
                                      engine="prefix", m=2, k=16,
                                      stream_chunk=8)
        assert plan["shards_planned"] >= 2
        assert plan["max_clients_per_shard"] <= 4096 + 64
        assert plan["n_shards"] <= len(jax.devices())
        assert plan["clients_per_shard"] * plan["n_shards"] >= 8192
        assert plan["projected_hbm_bytes_per_shard"] > 0

    def test_no_budget_falls_back_to_device_count(self, monkeypatch):
        import bench

        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES", "0")
        plan = bench.plan_mesh_shards(1000, None, ring=10,
                                      engine="prefix", m=2, k=16)
        assert plan["shards_planned"] is None
        assert plan["n_shards"] == len(jax.devices())

    def test_explicit_shards_capped_at_devices(self, monkeypatch):
        import bench

        monkeypatch.setenv("DMCLOCK_HBM_BUDGET_BYTES", "0")
        plan = bench.plan_mesh_shards(
            1000, len(jax.devices()) + 7, ring=10, engine="prefix",
            m=2, k=16)
        assert plan["n_shards"] == len(jax.devices())


class TestMeshRoundsComposition:
    def test_chunked_launches_compose(self):
        """Two fused cluster-mesh launches of E/2 rounds each, with
        views/metrics threaded through, == one launch of E rounds."""
        import jax.numpy as jnp

        from dmclock_tpu.core import ClientInfo
        from dmclock_tpu.parallel import cluster as CL
        from dmclock_tpu.robust import cluster as RC

        S, C, E, k = 4, 10, 6, 12
        mesh = CL.make_mesh(S)
        infos = [ClientInfo(10.0, 1.0 + (c % 3), 0.0)
                 for c in range(C)]

        def fresh():
            cl = CL.init_cluster(S, C)
            cl = CL.install_clients(
                cl,
                jnp.asarray([i.reservation_inv_ns for i in infos],
                            jnp.int64),
                jnp.asarray([i.weight_inv_ns for i in infos],
                            jnp.int64),
                jnp.asarray([i.limit_inv_ns for i in infos],
                            jnp.int64))
            return CL.shard_cluster(cl, mesh)

        rng = np.random.Generator(np.random.PCG64(7))
        arrivals = rng.integers(0, 3, size=(E, S, C)).astype(np.int32)
        # K=2 with an ODD chunk split: the second launch starts at
        # global round 3, so its sync grid must come from round0
        # (local indexing would sync at 3, 5 instead of 4) -- the
        # chunked digest only matches the single launch if the grid
        # is global
        for K in (1, 2):
            vd, vr = CL.init_mesh_views(S, C)
            met = jnp.zeros((S, obsdev.NUM_METRICS), jnp.int64)
            cl = fresh()
            digs = []
            r0 = 0
            for half in (arrivals[:3], arrivals[3:]):
                out = CL.run_mesh_rounds(
                    cl, half, 1, mesh, decisions_per_step=k,
                    max_arrivals=2, advance_ns=10 ** 8,
                    counter_sync_every=K, round0=r0,
                    view_delta=vd, view_rho=vr, metrics=met)
                cl, vd, vr, met = (out.cluster, out.view_delta,
                                   out.view_rho, out.metrics)
                digs.extend(CL.mesh_decs_seq(out.decs))
                r0 += half.shape[0]
            one = CL.run_mesh_rounds(
                fresh(), arrivals, 1, mesh, decisions_per_step=k,
                max_arrivals=2, advance_ns=10 ** 8,
                counter_sync_every=K)
            assert RC.decision_digest(digs) == \
                RC.decision_digest(CL.mesh_decs_seq(one.decs)), \
                f"K={K} chunked composition diverged"
            assert np.array_equal(np.asarray(met),
                                  np.asarray(one.metrics))
            assert np.array_equal(np.asarray(vd),
                                  np.asarray(one.view_delta))


class TestMultichipRecordV2:
    """MULTICHIP record schema v2 (scripts/run_fullscale.py): the
    reader accepts v1 rounds (no schema key, no mesh block) and v2
    records carrying the mesh throughput trajectory."""

    @staticmethod
    def _load_reader():
        import importlib.util
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location(
            "run_fullscale", repo / "scripts" / "run_fullscale.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_reader_accepts_v1(self, tmp_path):
        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text('{"n_devices": 8, "rc": 0, "ok": true, '
                     '"skipped": false, "tail": "dryrun ok"}')
        rec = mod.load_multichip(str(p))
        assert rec["schema"] == 1
        assert rec["mesh"] is None
        assert rec["ok"] and rec["n_devices"] == 8
        assert rec["tail"] == "dryrun ok"

    def test_reader_accepts_real_v1_rounds(self):
        """Every recorded MULTICHIP_r* round must keep loading."""
        import glob
        from pathlib import Path

        mod = self._load_reader()
        repo = Path(__file__).resolve().parent.parent
        rounds = sorted(glob.glob(str(repo / "MULTICHIP_r0*.json")))
        assert rounds, "expected recorded MULTICHIP rounds"
        for p in rounds:
            rec = mod.load_multichip(p)
            assert rec["schema"] == 1
            assert rec["n_devices"] >= 1

    def test_reader_accepts_v2(self, tmp_path):
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text(_json.dumps({
            "schema": 2, "n_devices": 8, "rc": 0, "ok": True,
            "skipped": False, "tail": "dryrun ok",
            "mesh": {"dps": 1.5e6, "dps_per_shard_mean": 2e5,
                     "n_shards": 8, "counter_sync_every": 2,
                     "counter_bytes_per_epoch": 100000,
                     "clients_total": 100000}}))
        rec = mod.load_multichip(str(p))
        assert rec["schema"] == 2
        assert rec["mesh"]["dps"] == 1.5e6
        assert rec["mesh"]["counter_sync_every"] == 2

    def test_v2_mesh_defaults_normalized(self, tmp_path):
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text(_json.dumps({
            "schema": 2, "n_devices": 4, "rc": 0, "ok": True,
            "tail": "", "mesh": {"dps": 5.0}}))
        rec = mod.load_multichip(str(p))
        assert rec["mesh"]["n_shards"] == 4
        assert rec["mesh"]["counter_sync_every"] == 1
        assert rec["mesh"]["counter_bytes_per_epoch"] == 0
        # pre-chaos v2 records normalize to a clean run (backward
        # compatibility of the PR-15 chaos fields)
        assert rec["mesh"]["fault_plan"] == "none"
        assert rec["mesh"]["fault_dropouts_per_shard"] == []
        assert rec["mesh"]["faults_injected_total"] == 0

    def test_v2_chaos_fields_round_trip(self, tmp_path):
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text(_json.dumps({
            "schema": 2, "n_devices": 8, "rc": 0, "ok": True,
            "tail": "", "mesh": {
                "dps": 1e6, "n_shards": 8,
                "fault_plan": "T32xS8:drop12+resync11+inject138",
                "fault_dropouts_per_shard": [2] * 8,
                "fault_resyncs_per_shard": [1] * 8,
                "faults_injected_total": 138}}))
        rec = mod.load_multichip(str(p))
        assert rec["mesh"]["fault_plan"].startswith("T32xS8")
        assert sum(rec["mesh"]["fault_dropouts_per_shard"]) == 16
        assert rec["mesh"]["faults_injected_total"] == 138

    def test_v1_v2_normalize_rebalance_none(self, tmp_path):
        """Pre-v3 records read back with rebalance=None (never a
        KeyError in history tooling)."""
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text('{"n_devices": 8, "rc": 0, "ok": true, '
                     '"tail": ""}')
        assert mod.load_multichip(str(p))["rebalance"] is None
        p.write_text(_json.dumps({
            "schema": 2, "n_devices": 8, "rc": 0, "ok": True,
            "tail": "", "mesh": {"dps": 1e6}}))
        assert mod.load_multichip(str(p))["rebalance"] is None

    def test_reader_accepts_v3(self, tmp_path):
        """v3 carries the rebalance block (bench_mesh_rebalance row):
        placement mode, migrations + per-move log, skew before/after,
        the recovery currencies."""
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text(_json.dumps({
            "schema": 3, "n_devices": 4, "rc": 0, "ok": True,
            "tail": "", "mesh": {"dps": 1e6, "n_shards": 4},
            "rebalance": {
                "placement": "p2c", "migrations": 4,
                "migration_log": [[4, 48, 0, 2], [4, 56, 0, 3]],
                "shard_skew_before": 3.26, "shard_skew_after": 2.83,
                "recovered_dps": -700.0,
                "recovered_decisions": 136}}))
        rec = mod.load_multichip(str(p))
        assert rec["schema"] == 3
        assert rec["rebalance"]["placement"] == "p2c"
        assert rec["rebalance"]["migrations"] == 4
        assert rec["rebalance"]["migration_log"][0] == [4, 48, 0, 2]
        assert rec["rebalance"]["shard_skew_before"] > \
            rec["rebalance"]["shard_skew_after"]
        # v2 mesh normalization still applies underneath
        assert rec["mesh"]["counter_sync_every"] == 1

    def test_v3_rebalance_defaults_normalized(self, tmp_path):
        import json as _json

        mod = self._load_reader()
        p = tmp_path / "r.json"
        p.write_text(_json.dumps({
            "schema": 3, "n_devices": 4, "rc": 0, "ok": True,
            "tail": "", "mesh": {"dps": 1e6},
            "rebalance": {}}))
        rec = mod.load_multichip(str(p))
        r = rec["rebalance"]
        assert r["placement"] == "p2c" and r["migrations"] == 0
        assert r["migration_log"] == []
        assert r["shard_skew_before"] == 0.0
        assert r["recovered_decisions"] == 0


# ----------------------------------------------------------------------
# degraded-mode mesh serving (ISSUE-15; docs/ROBUSTNESS.md
# "Degraded-mode mesh")
# ----------------------------------------------------------------------

CHAOS_SPEC = {"seed": 11, "p_dropout": 0.3, "mean_outage_steps": 2.0,
              "p_delay": 0.2, "p_dup": 0.2, "max_skew_ns": 1000}


def _chaos_chunk_pair(name: str, K: int, *, S: int = 4, E: int = 6,
                      seed: int = 11):
    """Run ONE seeded chaos chunk fused (run_mesh_chunk_guarded) and
    on the host robust loop (mesh_chunk_host_replay) from identical
    inputs; returns (fused, host, plan, job)."""
    from dmclock_tpu.robust import faults as F
    from dmclock_tpu.robust.guarded import (mesh_chunk_host_replay,
                                            run_mesh_chunk_guarded)

    job = mesh_job(name, n_shards=S, epochs=E, ckpt_every=E,
                   counter_sync_every=K)
    plan = F.sample_plan(seed, E, S, p_dropout=0.3,
                         mean_outage_steps=2.0, p_delay=0.2,
                         p_dup=0.2, max_skew_ns=1000)
    mesh = M.make_mesh(S)
    state = M.stack_shards(
        SV._job_state(dataclasses.replace(job, engine_loop="stream")),
        S, mesh)
    cd, cr, vd, vr = M.counter_init(S, job.n)
    rng = np.random.Generator(np.random.PCG64(9))
    counts = rng.poisson(1.0, (S, E, job.n)).astype(np.int32)
    fc = F.plan_chunk(plan, 0, E)
    kw = dict(engine=job.engine, epochs=E, m=job.m, k=job.k,
              chain_depth=job.chain_depth,
              dt_epoch_ns=job.dt_epoch_ns, waves=job.waves,
              with_metrics=True, select_impl=job.select_impl,
              calendar_impl=job.calendar_impl,
              ladder_levels=job.ladder_levels, counter_sync_every=K)
    fused = run_mesh_chunk_guarded(state, cd, cr, vd, vr, 0, counts,
                                   mesh=mesh, faults=fc, **kw)
    host = mesh_chunk_host_replay(state, cd, cr, vd, vr, 0, counts,
                                  faults=fc, **kw)
    return fused, host, plan, job


def _rows_digest(g, epochs: int) -> str:
    import hashlib

    d = b"\x00" * 32
    for i in range(epochs):
        flat = tuple(r for grp in g.epochs[i] for r in grp)
        d = SV._digest_update(d, flat)
    return hashlib.sha256(d).hexdigest()


def _fold_rows_metrics(g, epochs: int) -> np.ndarray:
    met = np.zeros(obsdev.NUM_METRICS, dtype=np.int64)
    for i in range(epochs):
        for grp in g.epochs[i]:
            for r in grp:
                met = obsdev.metrics_combine_np(
                    met, jax.device_get(r.metrics))
    return met


class TestMeshChaos:
    """The fault plane INSIDE the fused chunk: a seeded chaos mesh
    chunk must be decision-for-decision, counter-view-for-counter-
    view, and fault-counter-row identical to the host robust loop
    under the same plan -- and an all-benign plan bit-identical to no
    fault plumbing at all."""

    def test_zero_fault_chaos_job_bit_identical(self):
        plain = SV.run_job(mesh_job("prefix-sort", n_shards=2))
        zero = SV.run_job(mesh_job("prefix-sort", n_shards=2,
                                   fault_plan={"seed": 3}))
        assert_core_equal(zero, plain)
        assert zero.mesh_fallbacks == 0
        assert zero.mesh_chaos_fallbacks == 0

    # one engine stays in the quick sweep; the full engine x K matrix
    # runs slow-marked (scripts/run_tests.sh + ci.sh mesh chaos smoke)
    @pytest.mark.parametrize("name,K", [
        ("prefix-sort", 2),
        pytest.param("chain", 1, marks=pytest.mark.slow),
        pytest.param("chain", 4, marks=pytest.mark.slow),
        pytest.param("calendar-minstop", 4,
                     marks=pytest.mark.slow),
        pytest.param("calendar-minstop", 1,
                     marks=pytest.mark.slow),
        pytest.param("prefix-sort", 1, marks=pytest.mark.slow),
        pytest.param("prefix-sort", 4, marks=pytest.mark.slow),
        pytest.param("prefix-radix", 2, marks=pytest.mark.slow),
        pytest.param("calendar-bucketed", 2,
                     marks=pytest.mark.slow),
        pytest.param("calendar-wheel", 2,
                     marks=pytest.mark.slow),
    ])
    def test_chaos_chunk_equals_host_replay(self, name, K):
        """THE tentpole gate: fused seeded-chaos chunk == E
        host-driven robust steps (digest + counters + views + metric
        fold), at the staleness cadence K."""
        from dmclock_tpu.robust import faults as F

        fused, host, plan, job = _chaos_chunk_pair(name, K)
        E = 6
        assert fused.mesh_fallback == 0, \
            "gate must compare the FUSED path, not its own fallback"
        assert host.mesh_fallback == 1
        assert _rows_digest(fused, E) == _rows_digest(host, E)
        for f in ("cd", "cr", "view_d", "view_r"):
            assert np.array_equal(
                np.asarray(jax.device_get(getattr(fused, f))),
                np.asarray(jax.device_get(getattr(host, f)))), f
        assert fused.counts == host.counts
        mf = _fold_rows_metrics(fused, E)
        assert np.array_equal(mf, _fold_rows_metrics(host, E))
        ev = F.plan_events(plan)
        md = obsdev.metrics_dict(mf)
        for key in ("server_dropouts", "tracker_resyncs",
                    "faults_injected"):
            assert md[key] == ev[key], (key, md[key], ev[key])

    def test_supervised_chaos_counters_match_oracle(self):
        """Supervisor-level: a chaos mesh job's metric totals carry
        the plan oracle's fault rows exactly, and per-shard counts
        are recoverable from the oracle."""
        from dmclock_tpu.robust import faults as F

        job = mesh_job("prefix-sort", n_shards=4,
                       fault_plan=CHAOS_SPEC)
        r = SV.run_job(job)
        plan = F.plan_from_spec(F.parse_fault_spec(dict(CHAOS_SPEC)),
                                job.epochs, 4)
        ev = F.plan_events(plan)
        md = obsdev.metrics_dict(r.metrics)
        for key in ("server_dropouts", "tracker_resyncs",
                    "faults_injected"):
            assert md[key] == ev[key]
        per = F.plan_shard_events(plan)
        assert per["server_dropouts"].sum() == ev["server_dropouts"]
        assert per["faults_injected"].sum() == ev["faults_injected"]
        # chaos serves fewer decisions than the clean twin (shards
        # were down), but never zero -- degraded, not dead
        clean = SV.run_job(mesh_job("prefix-sort", n_shards=4))
        assert 0 < r.decisions < clean.decisions

    def test_chaos_fallback_replays_on_host_loop(self):
        """A guard trip DURING a chaos chunk (tag32 window blown)
        discards it and replays the identical fault schedule on the
        host robust loop -- counted as mesh_chaos_fallbacks, and
        deterministic (two runs agree on everything)."""
        trip = dict(tag_width=32, tag_spread_ns=1 << 33,
                    fault_plan=CHAOS_SPEC)
        a = SV.run_job(mesh_job("prefix-sort", n_shards=2, **trip))
        b = SV.run_job(mesh_job("prefix-sort", n_shards=2, **trip))
        assert a.mesh_chaos_fallbacks > 0
        assert a.mesh_chaos_fallbacks == a.mesh_fallbacks
        assert_core_equal(a, b)
        assert np.array_equal(a.mesh_counters, b.mesh_counters)

    def test_publish_shard_faults_labels(self):
        from dmclock_tpu.obs.registry import MetricsRegistry
        from dmclock_tpu.robust import faults as F

        plan = F.sample_plan(5, 12, 3, p_dropout=0.4, p_dup=0.3)
        per = F.plan_shard_events(plan)
        mat = np.stack([per["server_dropouts"],
                        per["tracker_resyncs"],
                        per["faults_injected"]], axis=1)
        reg = MetricsRegistry()
        obsdev.publish_shard_faults(reg, mat)
        text = reg.prometheus()
        total = int(per["server_dropouts"].sum())
        assert (f'dmclock_fault_server_dropouts_total'
                f'{{shard="all"}} {total}') in text
        assert 'dmclock_fault_injected_total{shard="0"}' in text


class TestMeshChaosCrashEquivalence:
    """SIGKILL mid-chaos-mesh-chunk (and mid-churn-mesh-chunk): the
    crash-equivalence matrix over kill points x {chaos, churn} x
    engines, with a slow spawn-mode REAL SIGKILL."""

    def _chaos_job(self, name, **over):
        over.setdefault("n_shards", 4)
        return mesh_job(name, fault_plan=CHAOS_SPEC, **over)

    def _churn_job(self, name, **over):
        from dmclock_tpu.lifecycle import churn as churn_mod

        spec = churn_mod.make_spec("churn_storm", total_ids=32,
                                   seed=3)
        return mesh_job(name, n_shards=4, churn=spec, epochs=8,
                        **over)

    @pytest.mark.parametrize("mode,name,frac", [
        ("chaos", "prefix-sort", 0.35),
        ("churn", "prefix-sort", 0.6),
        pytest.param("chaos", "prefix-sort", 0.75,
                     marks=pytest.mark.slow),
        pytest.param("chaos", "chain", 0.5,
                     marks=pytest.mark.slow),
        pytest.param("chaos", "calendar-minstop", 0.5,
                     marks=pytest.mark.slow),
        pytest.param("churn", "chain", 0.35,
                     marks=pytest.mark.slow),
        pytest.param("churn", "calendar-minstop", 0.75,
                     marks=pytest.mark.slow),
    ])
    def test_sigkill_matrix(self, tmp_path, mode, name, frac):
        job = self._chaos_job(name) if mode == "chaos" \
            else self._churn_job(name)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(int(ref.decisions * frac), 1),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)

    def test_kill_during_save_mid_chaos(self, tmp_path):
        job = self._chaos_job("prefix-sort")
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(kill_at_save=((1, "data_written"),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)

    @pytest.mark.slow
    def test_spawn_sigkill_mid_chaos(self, tmp_path):
        """Spawn mode: a REAL SIGKILL in a child interpreter mid-
        chaos, plus the result-file round-trip of the chaos fields."""
        job = self._chaos_job("prefix-sort", n_shards=2)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(int(ref.decisions * 0.5), 1),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan,
                                mode="spawn")
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)
        assert sup.mesh_chaos_fallbacks == ref.mesh_chaos_fallbacks


class TestMeshChurn:
    """Per-shard slot maps: EpochJob(engine_loop='mesh', churn=...)
    routes REGISTER/UPDATE/EVICT/IDLE by client->shard ownership
    (cid % n_shards) through S independent LifecyclePlanes, and the
    dynamic==static canonical-digest gate extends to S>1."""

    def _gate(self, name, scenario, S, total=32, epochs=8, **spec_kw):
        from dmclock_tpu.lifecycle import churn as churn_mod

        spec = churn_mod.make_spec(scenario, total_ids=total, seed=3,
                                   **spec_kw)
        dyn = SV.run_job(mesh_job(name, n_shards=S, churn=spec,
                                  epochs=epochs))
        st = SV.run_job(mesh_job(
            name, n_shards=S, epochs=epochs,
            churn=churn_mod.static_variant(spec)))
        assert dyn.digest == st.digest, \
            f"{scenario} S={S}: dynamic != static canonical digest"
        assert dyn.decisions == st.decisions > 0
        return dyn

    @pytest.mark.parametrize("name,scenario,S", [
        ("prefix-sort", "churn_storm", 4),
        pytest.param("prefix-sort", "churn_storm", 1,
                     marks=pytest.mark.slow),
        pytest.param("chain", "flash_crowd", 4,
                     marks=pytest.mark.slow),
        pytest.param("calendar-minstop", "churn_storm", 2,
                     marks=pytest.mark.slow),
        pytest.param("prefix-radix", "flash_crowd", 2,
                     marks=pytest.mark.slow),
    ])
    def test_dynamic_equals_static_at_s(self, name, scenario, S):
        dyn = self._gate(name, scenario, S)
        assert dyn.lifecycle["registrations"] > 0
        if S > 1:
            assert len(dyn.lifecycle["shards"]) == S

    def test_ownership_routing_is_exact(self):
        """Every registration lands on its owner shard: per-shard
        snapshots count exactly the ids with cid % S == s."""
        from dmclock_tpu.lifecycle import churn as churn_mod
        from dmclock_tpu.lifecycle.slots import owned_ids

        spec = churn_mod.make_spec("diurnal", total_ids=32, seed=3)
        dyn = SV.run_job(mesh_job("prefix-sort", n_shards=4,
                                  churn=spec, epochs=8))
        for s, shot in enumerate(dyn.lifecycle["shards"]):
            assert shot["registrations"] == len(owned_ids(32, s, 4))

    def test_shard_skew_imbalance_workload(self):
        """The first IMBALANCE workload (ROADMAP rack-scheduling
        entry point): one shard's Zipf head melts while the others
        idle -- visible in the per-shard completion counters, and
        still digest-equal to its static variant."""
        from dmclock_tpu.lifecycle import churn as churn_mod

        skew = churn_mod.make_spec("shard_skew", total_ids=64,
                                   base_lam=1.0, n_shards=4)
        job = mesh_job("prefix-sort", n_shards=4, churn=skew,
                       epochs=8, waves=4)
        dyn = SV.run_job(job)
        st = SV.run_job(dataclasses.replace(
            job, churn=churn_mod.static_variant(skew)))
        assert dyn.digest == st.digest
        per_shard = dyn.mesh_counters[0].sum(axis=1)
        hot, cold = per_shard[0], per_shard[1:]
        assert hot > 4 * cold.max(), \
            (f"hot shard should melt while others idle: "
             f"{per_shard.tolist()}")

    def test_churn_mesh_crash_equivalent(self, tmp_path):
        from dmclock_tpu.lifecycle import churn as churn_mod

        spec = churn_mod.make_spec("churn_storm", total_ids=32,
                                   seed=3)
        job = mesh_job("prefix-sort", n_shards=4, churn=spec,
                       epochs=8, with_ledger=True)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(int(ref.decisions * 0.5), 1),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)


class TestMeshFlight:
    """Per-shard flight rings (the PR-13 leftover): each shard
    records its own commits in its own HBM ring; the host merges in
    deterministic shard order at drain."""

    def test_s1_flight_bit_identical_to_stream(self):
        fl = dict(flight_records=16)
        s = SV.run_job(dataclasses.replace(
            JOBS["prefix-sort"], engine_loop="stream", **fl))
        m = SV.run_job(mesh_job("prefix-sort", **fl))
        assert_core_equal(m, s)
        assert np.array_equal(m.flight_buf, s.flight_buf)
        assert m.flight_seq == s.flight_seq

    def test_s4_merge_deterministic_and_ordered(self):
        a = SV.run_job(mesh_job("prefix-sort", n_shards=4,
                                flight_records=16))
        b = SV.run_job(mesh_job("prefix-sort", n_shards=4,
                                flight_records=16))
        assert np.array_equal(a.flight_buf, b.flight_buf)
        assert a.flight_seq == b.flight_seq > 0
        # shard-major merge: within each shard's span the seq column
        # is strictly increasing (ring rows in write order)
        seqs = a.flight_buf[:, 0]
        drops = int((np.diff(seqs) < 0).sum())
        assert drops <= 3, "more seq resets than shard boundaries"

    @pytest.mark.slow
    def test_s2_flight_crash_equivalent(self, tmp_path):
        job = mesh_job("prefix-sort", n_shards=2, flight_records=16)
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(int(ref.decisions * 0.5), 1),))
        sup = SV.run_supervised(job, tmp_path / "wd", plan)
        assert sup.restarts >= 1
        SV.assert_crash_equivalent(sup, ref)
