"""TPU push-mode queue tests (reference PushPriorityQueue semantics,
dmclock_server.h:1504-1797): autonomous dispatch via handle_f, the
can_handle gate, batch dispatch via capacity_f, the sched-ahead timed
wakeup, and dispatch-order parity with the oracle push queue."""

import threading
import time

from dmclock_tpu import AtLimit
from dmclock_tpu.core import (ClientInfo, Phase, PushPriorityQueue,
                              ReqParams, sec_to_ns)
from dmclock_tpu.engine import TpuPushPriorityQueue


def wait_until(pred, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return pred()


class TestTpuPushQueue:
    def test_immediate_dispatch(self):
        handled = []
        q = TpuPushPriorityQueue(lambda c: ClientInfo(0, 1, 0),
                                 can_handle_f=lambda: True,
                                 handle_f=lambda c, r, p, cost:
                                 handled.append((c, r, p, cost)))
        try:
            q.add_request("req1", 7, ReqParams())
            assert wait_until(lambda: len(handled) == 1)
            assert handled[0][0] == 7
            assert handled[0][2] is Phase.PRIORITY
            assert q.prop_sched_count == 1
        finally:
            q.shutdown()

    def test_can_handle_gates_dispatch(self):
        handled = []
        gate = {"open": False}
        q = TpuPushPriorityQueue(lambda c: ClientInfo(0, 1, 0),
                                 can_handle_f=lambda: gate["open"],
                                 handle_f=lambda c, r, p, cost:
                                 handled.append(r))
        try:
            q.add_request("r", 1, ReqParams())
            time.sleep(0.05)
            assert handled == []
            gate["open"] = True
            q.request_completed()  # server signals capacity
            assert wait_until(lambda: handled == ["r"])
        finally:
            q.shutdown()

    def test_capacity_batch_dispatch(self):
        """capacity_f > 1 drains several decisions per device launch."""
        handled = []
        q = TpuPushPriorityQueue(lambda c: ClientInfo(0, 1, 0),
                                 can_handle_f=lambda: True,
                                 handle_f=lambda c, r, p, cost:
                                 handled.append((c, r)),
                                 capacity_f=lambda: 8)
        try:
            now = sec_to_ns(time.time())
            for i in range(6):
                q.add_request(f"r{i}", i % 2, ReqParams(), time_ns=now)
            assert wait_until(lambda: len(handled) == 6)
            assert sorted(r for _c, r in handled) == \
                sorted(f"r{i}" for i in range(6))
        finally:
            q.shutdown()

    def test_sched_ahead_timed_wakeup(self):
        # a future-limited request is dispatched by the sched-ahead
        # thread once its limit restores, without further prompting
        handled = []
        q = TpuPushPriorityQueue(lambda c: ClientInfo(0, 1, 10),
                                 can_handle_f=lambda: True,
                                 handle_f=lambda c, r, p, cost:
                                 handled.append(r),
                                 at_limit=AtLimit.WAIT)
        try:
            now = sec_to_ns(time.time())
            # two requests: limit 10/s -> second eligible ~0.1s later
            q.add_request("a", 1, ReqParams(), time_ns=now)
            q.add_request("b", 1, ReqParams(), time_ns=now)
            assert wait_until(lambda: len(handled) == 2)
        finally:
            q.shutdown()

    def test_shutdown_joins_thread(self):
        q = TpuPushPriorityQueue(lambda c: ClientInfo(0, 1, 0),
                                 can_handle_f=lambda: False,
                                 handle_f=lambda *a: None)
        q.shutdown()
        assert not q._sched_thd.is_alive()

    def test_dispatch_order_parity_with_oracle(self):
        """Same weighted backlog, same virtual arrival times: the TPU
        push queue must hand requests to handle_f in the same order as
        the oracle push queue (weights 1:2 under a shared gate that
        admits one dispatch per completion)."""

        def run(queue_cls, **kw):
            handled = []
            gate = {"tokens": 0}
            lock = threading.Lock()

            def can_handle():
                with lock:
                    return gate["tokens"] > 0

            def handle(c, r, p, cost):
                with lock:
                    gate["tokens"] -= 1
                handled.append((c, r))

            q = queue_cls(
                lambda c: ClientInfo(0, 1.0 if c == 1 else 2.0, 0),
                can_handle_f=can_handle, handle_f=handle, **kw)
            try:
                now = sec_to_ns(time.time())
                for i in range(6):
                    q.add_request(f"a{i}", 1, ReqParams(), time_ns=now)
                    q.add_request(f"b{i}", 2, ReqParams(), time_ns=now)
                for i in range(12):
                    with lock:
                        gate["tokens"] += 1
                    q.request_completed()
                    assert wait_until(lambda: len(handled) == i + 1), \
                        f"stalled at dispatch {i} ({handled})"
            finally:
                q.shutdown()
            return handled

        oracle = run(PushPriorityQueue, run_gc_thread=False)
        tpu = run(TpuPushPriorityQueue)
        assert oracle == tpu
        # weight 2 client gets twice the share while both have backlog
        # (the full drain is 6:6 by construction)
        first6 = [c for c, _r in tpu[:6]]
        assert first6.count(2) == 2 * first6.count(1)
