"""Closed-loop serving controller (control/; docs/CONTROLLER.md).

The headline gates:

- **controller=off == bare**: ``EpochJob(controller=None)`` (and
  ``False``) is bit-identical to the bare runner -- zero plumbing
  cost, so every actuation stays digest-explainable against the off
  twin;
- **cross-loop identity**: the same controller-on job produces the
  same decision digest AND the same journal trajectory on the round,
  stream, and S=1 mesh loops (the actuation grid is the shared
  checkpoint-boundary grid);
- **SIGKILL matrix**: a kill at ``before_journal`` /
  ``after_journal`` / ``after_apply`` around any decision resumes to
  the exact knob trajectory of the uninterrupted twin
  (fsync-before-apply + replay-not-re-decide), with
  ``before_journal`` replaying zero journal entries and the
  post-write stages replaying at least one;
- plus the pure-policy unit gates (hysteresis, cooldown, fixed-order
  chaining), the WAL journal's torn-tail truncation, and the
  satellite-1 churn+provenance composition the boundary ``extras``
  rider unlocked.
"""

import dataclasses
import json

import numpy as np
import pytest

from dmclock_tpu.control import (Controller, ControllerConfig,
                                 as_spec)
from dmclock_tpu.control import journal as journal_mod
from dmclock_tpu.control import policy as pol
from dmclock_tpu.control import signals as sigs
from dmclock_tpu.lifecycle import make_spec
from dmclock_tpu.robust import host_faults as HF
from dmclock_tpu.robust import supervisor as SV


def mk_sig(epoch=2, **kw):
    """A synthetic all-quiet boundary snapshot; override per test."""
    base = dict(epoch=epoch, backlog=0, live=0, capacity=0,
                resv_miss_d=0, limit_break_d=0, share_skew_d=0,
                violations_d=0, guard_trips_d=0, ingest_drops_d=0,
                ladder_steps_d=0, starvation_ns=0)
    base.update(kw)
    base.setdefault("press_backlog", base["backlog"])
    return sigs.ControlSignals(**base)


# a fully-resolved spec for the pure-policy units (no auto fields)
SPEC = dict(pol.DEFAULT_SPEC, backlog_hi=100, occ_floor=4,
            ladder_max=3)

# the supervised-run spec that FORCES actuation: backlog_hi=1 makes
# every boundary pressured, so clamp_down fires at the very first one
FORCED = {"backlog_hi": 1}

JOB = SV.EpochJob(engine="prefix", n=96, depth=6, ring=10, epochs=8,
                  m=2, k=16, seed=5, arrival_lam=1.0, waves=2,
                  ckpt_every=2)

_REFS: dict = {}


def ref_of(loop: str, controller=True) -> SV.SupervisedResult:
    key = (loop, repr(controller))
    if key not in _REFS:
        _REFS[key] = SV.run_job(dataclasses.replace(
            JOB, engine_loop=loop, controller=controller))
    return _REFS[key]


class TestSignals:
    def test_digest_reads_deterministic_tier_only(self):
        a = mk_sig(backlog=7, resv_miss_d=1)
        b = a._replace(retraces=9, compile_ms=3.5, bound_class="hbm",
                       dispatch_share=0.4, fallbacks=2)
        assert sigs.digest(a) == sigs.digest(b)

    def test_digest_changes_on_deterministic_field(self):
        a = mk_sig(backlog=7)
        assert sigs.digest(a) != sigs.digest(a._replace(backlog=8))
        assert sigs.digest(a) != sigs.digest(a._replace(epoch=3))


class TestMigratePeakBranch:
    """The satellite pressure feed: the migrate rule's two
    interchangeable skew reads (boundary depth vs mid-epoch peaks)."""

    MSPEC = dict(pol.DEFAULT_SPEC, hysteresis=1,
                 migrate_skew_hi=1.5, migrate_shards=4)

    def _fire(self, sig):
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        _, dec = pol.step(ps, [1, 0, 100, 0, 0], sig, self.MSPEC)
        return [r for r, _k in dec]

    def test_peaks_arm_with_zero_boundary_depth(self):
        """The calendar shape: depth fully drained at the boundary
        (backlog == 0) but the mid-epoch peaks show the skew."""
        sig = mk_sig(backlog=0, press_peak=12, backlog_peak=12)
        assert "migrate" in self._fire(sig)

    def test_depth_read_still_arms_without_peaks(self):
        sig = mk_sig(backlog=8, press_backlog=8)
        assert "migrate" in self._fire(sig)

    def test_balanced_peaks_stay_quiet(self):
        # 4 shards x peak 3 each: hottest * S == 12 == backlog_peak,
        # not > 1.5x -- no skew, no fire
        sig = mk_sig(backlog=0, press_peak=3, backlog_peak=12)
        assert "migrate" not in self._fire(sig)

    def test_defaults_keep_peak_branch_inert(self):
        """Round/stream loops never feed peaks: the defaulted fields
        leave the rule exactly as before."""
        sig = mk_sig(backlog=0)
        assert sig.press_peak == 0 and sig.backlog_peak == 0
        assert "migrate" not in self._fire(sig)

    def test_peaks_ride_the_deterministic_digest(self):
        a = mk_sig(press_peak=5, backlog_peak=9)
        assert sigs.digest(a) != sigs.digest(a._replace(press_peak=6))
        assert "press_peak" in sigs.DETERMINISTIC_FIELDS
        assert "backlog_peak" in sigs.DETERMINISTIC_FIELDS

    def test_collect_reduces_per_shard_peaks(self):
        from dmclock_tpu.obs import provenance as obsprov

        ctl = Controller(dict(self.MSPEC), n=8, ring=4, n_shards=4)
        press = np.zeros((4, obsprov.PRESS_FIELDS), dtype=np.int64)
        press[:, obsprov.PRESS_BACKLOG] = (9, 1, 2, 0)
        sig = ctl.collect(2, press=press)
        assert sig.press_peak == 9
        assert sig.backlog_peak == 12


class TestPolicy:
    def test_down_rule_fires_first_triggering_boundary(self):
        """Protective moves have hysteresis 1: one resv-miss episode
        snaps the sync grid to sync_min immediately."""
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        ps, dec = pol.step(ps, [4, 0, 100, 0, 0],
                           mk_sig(resv_miss_d=1), SPEC)
        assert dec == [("staleness_down", [1, 0, 100, 0, 0])]

    def test_up_rule_needs_clean_streak(self):
        """Relaxing moves need ``hysteresis`` consecutive clean
        boundaries -- the anti-flap half of the table."""
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        ps, dec = pol.step(ps, [1, 0, 100, 0, 0], mk_sig(), SPEC)
        assert dec == []            # streak 1 of 2: no decision yet
        ps, dec = pol.step(ps, [1, 0, 100, 0, 0], mk_sig(epoch=4), SPEC)
        assert dec == [("staleness_up", [2, 0, 100, 0, 0])]

    def test_dirty_boundary_resets_the_streak(self):
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        ps, _ = pol.step(ps, [1, 0, 100, 0, 0], mk_sig(), SPEC)
        # a guard trip breaks the clean streak (and fires ladder_down)
        ps, dec = pol.step(ps, [1, 0, 100, 0, 0],
                           mk_sig(epoch=4, guard_trips_d=1), SPEC)
        assert ("staleness_up", [2, 0, 100, 0, 0]) not in dec
        ps, dec = pol.step(ps, [1, 0, 100, 0, 0], mk_sig(epoch=6), SPEC)
        assert dec == []            # streak restarted at 1

    def test_cooldown_inert_then_refires(self):
        """An applied decision cools its rule for ``cooldown``
        boundaries; the trigger persisting past the cooldown fires
        again."""
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        knobs = [1, 0, 100, 0, 0]
        fired = []
        for e in (2, 4, 6, 8):
            ps, dec = pol.step(ps, knobs,
                               mk_sig(epoch=e, guard_trips_d=1), SPEC)
            for rule, new in dec:
                knobs = new
            fired.append([r for r, _ in dec])
        assert fired == [["ladder_down"], [], [], ["ladder_down"]]
        assert knobs[pol.KNOB_LADDER] == 2

    def test_fixed_order_knob_chaining(self):
        """Later rules see earlier rules' knob updates within one
        boundary -- the fixed RULES order keeps a multi-rule boundary
        deterministic."""
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        sig = mk_sig(resv_miss_d=1, guard_trips_d=1, limit_break_d=1)
        ps, dec = pol.step(ps, [4, 0, 100, 0, 0], sig, SPEC)
        assert [r for r, _ in dec] == \
            ["staleness_down", "ladder_down", "clamp_down"]
        assert [new for _, new in dec] == \
            [[1, 0, 100, 0, 0], [1, 1, 100, 0, 0], [1, 1, 75, 0, 0]]

    def test_clamp_floor_and_ladder_ceiling(self):
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        _, dec = pol.step(ps, [1, 3, 25, 0, 0],
                          mk_sig(limit_break_d=1, guard_trips_d=1),
                          SPEC)
        assert dec == []        # clamp at clamp_min, ladder at max

    def test_compact_on_sparse_occupancy(self):
        # sync pinned at sync_max so the clean boundary exercises the
        # compact rule alone
        ps = np.zeros(2 * pol.NUM_RULES, dtype=np.int64)
        sig = mk_sig(live=3, capacity=16)
        ps, dec = pol.step(ps, [8, 0, 100, 0, 0], sig, SPEC)
        assert dec == []            # hysteresis 2
        _, dec = pol.step(ps, [8, 0, 100, 0, 0],
                          sig._replace(epoch=4), SPEC)
        assert dec == [("compact", [8, 0, 100, 1, 0])]

    def test_overlay_chains_ladder_rungs(self):
        from dmclock_tpu.robust.guarded import LADDER_RUNGS
        knob, fast, safe = LADDER_RUNGS[0]
        assert pol.overlay({knob: fast}, 0) == {knob: fast}
        assert pol.overlay({knob: fast}, 1)[knob] == safe
        # the shared-knob calendar rungs chain: two conceded levels
        # walk wheel -> bucketed -> minstop
        assert pol.overlay({knob: fast}, 2)[knob] == "minstop"
        # a config not on any rung's fast side passes through
        assert pol.overlay({"select_impl": "sort"}, 4) \
            == {"select_impl": "sort"}


class TestJournal:
    def test_append_asserts_sequential_seq(self, tmp_path):
        j = journal_mod.DecisionJournal(tmp_path)
        j.append({"seq": 0, "epoch": 2, "rule": "clamp_down",
                  "digest": "x", "old": [1, 0, 100, 0, 0],
                  "new": [1, 0, 75, 0, 0]})
        with pytest.raises(AssertionError):
            j.append({"seq": 2, "epoch": 4, "rule": "clamp_down",
                      "digest": "x", "old": [], "new": []})

    def test_reload_and_entry_at(self, tmp_path):
        j = journal_mod.DecisionJournal(tmp_path)
        for s in range(3):
            j.append({"seq": s, "epoch": 2 * (s + 1),
                      "rule": "clamp_down", "digest": "x",
                      "old": [1, 0, 100, 0, 0], "new": [1, 0, 75, 0, 0]})
        k = journal_mod.DecisionJournal(tmp_path)
        assert len(k) == 3
        assert k.entry_at(1)["epoch"] == 4
        assert k.entry_at(3) is None

    def test_torn_tail_truncated_on_open(self, tmp_path):
        j = journal_mod.DecisionJournal(tmp_path)
        j.append({"seq": 0, "epoch": 2, "rule": "clamp_down",
                  "digest": "x", "old": [1, 0, 100, 0, 0],
                  "new": [1, 0, 75, 0, 0]})
        with open(j.path, "a") as fh:    # kill landed mid-write
            fh.write('{"seq": 1, "epo')
        k = journal_mod.DecisionJournal(tmp_path)
        assert len(k) == 1
        # the tear is gone durably: a third open sees a clean file
        with open(k.path) as fh:
            lines = fh.read().splitlines()
        assert len(lines) == 1 and json.loads(lines[0])["seq"] == 0


class TestSpec:
    def test_as_spec_normalization(self):
        assert as_spec(None) is None
        assert as_spec(False) is None
        assert as_spec({"enabled": False}) is None
        full = as_spec(True)
        assert full["hysteresis"] == 2 and full["ladder_max"] > 0
        assert as_spec(ControllerConfig(clamp_min=10))["clamp_min"] \
            == 10
        with pytest.raises(AssertionError, match="unknown"):
            as_spec({"no_such_knob": 1})

    def test_clamp_counts_rng_neutral_cap(self):
        ctl = Controller(as_spec(True), n=4, ring=4)
        counts = np.array([5, 0, 9, 1], dtype=np.int64)
        assert ctl.clamp_counts(counts, 4) is counts  # 100% == off
        ctl.knobs[pol.KNOB_CLAMP] = 50
        assert ctl.clamp_counts(counts, 4).tolist() == [2, 0, 2, 1]
        ctl.knobs[pol.KNOB_CLAMP] = 25
        # the cap never reaches zero: admission is clamped, not shut
        assert ctl.clamp_counts(counts, 4).tolist() == [1, 0, 1, 1]


class TestOffGate:
    @pytest.mark.parametrize("loop", ["round", "stream"])
    def test_off_equals_bare(self, loop):
        """controller=False is bit-identical to the bare runner --
        the zero-plumbing gate that keeps every actuation
        explainable against the off twin."""
        bare = ref_of(loop, controller=None)
        off = ref_of(loop, controller=False)
        assert off.digest == bare.digest
        assert off.state_digest == bare.state_digest
        assert np.array_equal(np.asarray(off.metrics),
                              np.asarray(bare.metrics))
        assert off.controller_decisions == 0
        assert off.controller_knobs is None
        assert off.controller_trajectory is None


class TestForcedActuation:
    def test_clamp_down_fires_and_shapes_the_run(self):
        """backlog_hi=1 pressures every boundary: clamp_down fires at
        the first one, the knob drops below 100, and the clamped
        arrival stream leaves a different final state than the off
        twin (the actuation is real, not just journaled -- at this
        small shape the thinner backlog does not reorder the served
        decisions, so the divergence shows up in the state digest)."""
        on = SV.run_job(dataclasses.replace(JOB, controller=FORCED))
        off = ref_of("round", controller=None)
        assert on.controller_decisions > 0
        rules = [row[2] for row in on.controller_trajectory]
        assert "clamp_down" in rules
        assert on.controller_knobs[pol.KNOB_CLAMP] < 100
        assert on.state_digest != off.state_digest
        # first decision fires at the FIRST boundary of the grid
        assert on.controller_trajectory[0][1] == JOB.ckpt_every

    def test_quiet_controller_decides_but_never_clamps_rng(self):
        """With the default spec the quiet job only relaxes
        (staleness_up is a round-loop no-op knob), so the decision
        digest matches the off twin exactly -- actuation is
        digest-explainable."""
        on = ref_of("round", controller=True)
        off = ref_of("round", controller=None)
        assert on.digest == off.digest
        assert on.controller_knobs is not None


class TestCrossLoopIdentity:
    @pytest.mark.parametrize("loop", [
        "stream", pytest.param("mesh", marks=pytest.mark.slow)])
    def test_trajectory_identical_across_loops(self, loop):
        """The same forced-actuation job journals the same decisions
        (seq, epoch, rule, knobs) and lands the same digest on every
        loop -- the actuation grid IS the shared boundary grid."""
        r = _REFS.setdefault(("round", "forced"), SV.run_job(
            dataclasses.replace(JOB, controller=FORCED)))
        o = SV.run_job(dataclasses.replace(
            JOB, engine_loop=loop, controller=FORCED))
        assert o.digest == r.digest
        assert o.state_digest == r.state_digest
        assert o.controller_trajectory == r.controller_trajectory
        assert o.controller_knobs == r.controller_knobs


class TestSigkillMatrix:
    """Satellite 4: the kill lands at each stage of the
    fsync-before-apply window around a real decision; the resumed run
    must be crash-equivalent to the uninterrupted controller-on twin
    with the exactly-once replay accounting."""

    @pytest.mark.parametrize("loop", [
        "round", "stream", pytest.param("mesh",
                                        marks=pytest.mark.slow)])
    @pytest.mark.parametrize("stage", HF.CONTROLLER_STAGES)
    def test_kill_at_stage_resumes_exact(self, tmp_path, loop, stage):
        job = dataclasses.replace(JOB, engine_loop=loop,
                                  controller=FORCED)
        ref = _REFS.setdefault((loop, "forced"), SV.run_job(job))
        assert ref.controller_decisions > 0
        # kill around the decision at the SECOND boundary, so the
        # resume restores the first boundary's checkpoint and walks
        # back through a journaled decision
        epoch = 2 * JOB.ckpt_every
        assert any(row[1] == epoch for row in ref.controller_trajectory)
        plan = HF.HostFaultPlan(kill_at_controller=((epoch, stage),))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1
        if stage == "before_journal":
            # nothing durable yet: the resumed run RE-DECIDES (the
            # policy is pure) -- zero replays, identical trajectory
            assert res.controller_replays == 0
        else:
            # the entry was durable before the kill: the resumed run
            # REPLAYS it instead of re-deciding
            assert res.controller_replays >= 1

    def test_exactly_once_with_two_kills(self, tmp_path):
        """Two kills in one run (one per boundary window): every
        journaled seq is still applied exactly once."""
        job = dataclasses.replace(JOB, controller=FORCED)
        ref = _REFS.setdefault(("round", "forced"), SV.run_job(job))
        plan = HF.HostFaultPlan(kill_at_controller=(
            (JOB.ckpt_every, "after_journal"),
            (2 * JOB.ckpt_every, "after_apply")))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 2
        seqs = [row[0] for row in res.controller_trajectory]
        assert seqs == sorted(set(seqs))


@pytest.mark.slow
class TestSpawnSigkill:
    """REAL SIGKILL: the supervised child is a separate interpreter
    and the injector delivers an actual signal 9 mid-actuation."""

    @pytest.mark.parametrize("stage", HF.CONTROLLER_STAGES)
    def test_spawned_kill_mid_actuation(self, tmp_path, monkeypatch,
                                        stage):
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        job = dataclasses.replace(JOB, controller=FORCED)
        ref = _REFS.setdefault(("round", "forced"), SV.run_job(job))
        plan = HF.HostFaultPlan(
            kill_at_controller=((2 * JOB.ckpt_every, stage),))
        res = SV.run_supervised(job, tmp_path, plan, mode="spawn")
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1
        if stage == "before_journal":
            assert res.controller_replays == 0
        else:
            assert res.controller_replays >= 1


class TestChurnProvComposition:
    """Satellite 1: the lifecycle boundary now carries the provenance
    watermark through grow/compact/evict via the ``extras`` rider --
    the PR-12 with_prov+churn rejection is lifted."""

    def _job(self, loop="round"):
        spec = make_spec("churn_storm", total_ids=16, base_lam=1.5,
                         compact_every=1, gens=4, stride=4, life=2,
                         capacity0=4)
        return SV.EpochJob(engine="prefix", churn=spec, epochs=12,
                           m=2, k=8, ring=16, waves=4, ckpt_every=2,
                           seed=11, engine_loop=loop, with_prov=True)

    def test_round_equals_stream_with_prov_arrays(self):
        r = SV.run_job(self._job("round"))
        s = SV.run_job(self._job("stream"))
        assert r.digest == s.digest
        assert r.prov_scal is not None
        for f in ("prov_margin_hist", "prov_scal",
                  "prov_last_served"):
            assert np.array_equal(getattr(r, f), getattr(s, f)), f

    def test_crash_equivalent_through_compaction(self, tmp_path):
        job = self._job()
        ref = SV.run_job(job)
        plan = HF.HostFaultPlan(
            kill_at_decisions=(max(ref.decisions // 2, 1),))
        res = SV.run_supervised(job, tmp_path, plan)
        SV.assert_crash_equivalent(res, ref)
        assert res.restarts == 1
