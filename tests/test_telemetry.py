"""Device telemetry plane tests (obs.histograms / obs.flight).

The load-bearing contracts:

1. **On/off bit-identity** -- enabling any combination of histograms,
   ledger, and flight recorder must not perturb the decision stream or
   the final engine state, on all three epoch engines and the
   radix/tag32/bucketed fast paths (the telemetry is pure reductions
   over arrays the kernels already materialize).
2. **Cross-impl exactness** -- the telemetry CONTENTS are equal across
   fast paths that commit identical decision streams: sort == radix,
   tag32 == int64 (window holding), bucketed L=1 == minstop bitwise,
   and bucketed-L == the composition of L minstop batches (a ladder
   level IS one minstop batch).
3. **Device truth** -- the per-client ledger equals a host-side
   recomputation from the emitted decision streams (prefix) and the
   calendar served vectors (seeded cfg4-flavored run).
4. **Flight ring** -- wraparound keeps exactly the newest R records
   with a monotone seq, deterministically, including the
   one-batch-overflow case.
"""

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo, NS_PER_SEC
from dmclock_tpu.core.timebase import rate_to_inv_ns
from dmclock_tpu.engine.fastpath import (scan_calendar_epoch,
                                         scan_chain_epoch,
                                         scan_prefix_epoch)
from dmclock_tpu.obs import MetricsRegistry
from dmclock_tpu.obs import device as obsdev
from dmclock_tpu.obs import flight as obsflight
from dmclock_tpu.obs import histograms as obshist
from dmclock_tpu.robust.guarded import run_epoch_guarded

from engine_helpers import assert_states_equal, deep_state

S = NS_PER_SEC

INFOS = {
    0: ClientInfo(10.0, 2.0, 50.0),
    1: ClientInfo(5.0, 1.0, 40.0),
    2: ClientInfo(0.0, 3.0, 0.0),
}


def _mixed_state(depth=6):
    return deep_state(INFOS, depth)


def _kit(n, records=64):
    return dict(hists=obshist.hist_zero(),
                ledger=obshist.ledger_zero(n),
                flight=obsflight.flight_init(records))


# ----------------------------------------------------------------------
# bucket math
# ----------------------------------------------------------------------

class TestBucketing:
    def test_bucket_index_exact(self):
        v = jnp.asarray([-7, 0, 1, 2, 3, 4, 7, 8,
                         (1 << 46) - 1, 1 << 46, 1 << 60])
        idx = jax.device_get(obshist.bucket_index(v)).tolist()
        assert idx == [0, 0, 1, 2, 2, 3, 3, 4, 46, 47, 47]

    def test_observe_counts_and_sum(self):
        h = obshist.hist_zero()
        vals = jnp.asarray([0, 1, 5, 1000, -3], dtype=jnp.int64)
        mask = jnp.asarray([True, True, True, True, False])
        h = obshist.hist_observe(h, obshist.HIST_RESV_TARDINESS,
                                 vals, mask)
        d = obshist.hist_dict(h)["resv_tardiness_ns"]
        assert d["count"] == 4
        assert d["sum"] == 0 + 1 + 5 + 1000
        assert d["buckets"][0] == 1          # the 0
        assert d["buckets"][1] == 1          # the 1
        assert d["buckets"][3] == 1          # 5 in [4, 8)
        assert d["buckets"][10] == 1         # 1000 in [512, 1024)

    def test_observe_scalar_weight_zero(self):
        h = obshist.hist_zero()
        h = obshist.hist_observe_scalar(h, obshist.HIST_LIMIT_STALL,
                                        12345, 0)
        assert obshist.hist_dict(h)["limit_stall_ns"]["count"] == 0
        h = obshist.hist_observe_scalar(h, obshist.HIST_LIMIT_STALL,
                                        12345, 1)
        d = obshist.hist_dict(h)["limit_stall_ns"]
        assert d["count"] == 1 and d["sum"] == 12345

    def test_percentile_upper_bounds(self):
        h = np.zeros((obshist.NUM_HISTS, obshist.NUM_BUCKETS + 1),
                     dtype=np.int64)
        assert obshist.hist_percentile(h, 0, 0.99) == 0.0
        # 90 values in bucket 1 (v=1), 10 in bucket 10 (~1000)
        h[0, 1] = 90
        h[0, 10] = 10
        assert obshist.hist_percentile(h, 0, 0.50) == 1.0
        assert obshist.hist_percentile(h, 0, 0.99) == float(2**10 - 1)

    def test_combine_and_mirrors(self):
        a = obshist.hist_zero().at[0, 3].add(5).at[1, 48].add(100)
        b = obshist.hist_zero().at[0, 3].add(2)
        c = jax.device_get(obshist.hist_combine(a, b))
        assert c[0, 3] == 7 and c[1, 48] == 100
        la = obshist.ledger_zero(3).at[0].set(
            jnp.asarray([3, 1, 0, 50, 30], dtype=jnp.int64))
        lb = obshist.ledger_zero(3).at[0].set(
            jnp.asarray([2, 2, 1, 20, 40], dtype=jnp.int64))
        dev = jax.device_get(obshist.ledger_combine(la, lb))
        host = obshist.ledger_combine_np(jax.device_get(la),
                                         jax.device_get(lb))
        assert np.array_equal(dev, host)
        assert dev[0].tolist() == [5, 3, 1, 70, 40]  # max col maxes


# ----------------------------------------------------------------------
# on/off bit-identity across engines and fast paths
# ----------------------------------------------------------------------

ENGINE_RUNS = {
    "prefix-sort": lambda st, now, **tele: scan_prefix_epoch(
        st, now, 3, 4, anticipation_ns=0, with_metrics=True, **tele),
    "prefix-radix": lambda st, now, **tele: scan_prefix_epoch(
        st, now, 3, 4, anticipation_ns=0, select_impl="radix", **tele),
    "prefix-tag32": lambda st, now, **tele: scan_prefix_epoch(
        st, now, 3, 4, anticipation_ns=0, tag_width=32, **tele),
    "prefix-window": lambda st, now, **tele: scan_prefix_epoch(
        st, now, 4, 4, anticipation_ns=0, window_m=2, **tele),
    "chain": lambda st, now, **tele: scan_chain_epoch(
        st, now, 2, 4, chain_depth=3, anticipation_ns=0,
        use_pallas=False, with_metrics=True, **tele),
    "calendar-minstop": lambda st, now, **tele: scan_calendar_epoch(
        st, now, 2, steps=4, use_pallas=False, with_metrics=True,
        **tele),
    "calendar-bucketed": lambda st, now, **tele: scan_calendar_epoch(
        st, now, 2, steps=4, use_pallas=False,
        calendar_impl="bucketed", ladder_levels=2, **tele),
    "calendar-tag32": lambda st, now, **tele: scan_calendar_epoch(
        st, now, 2, steps=4, use_pallas=False, tag_width=32, **tele),
}

_DEC_FIELDS = {
    "prefix": ("count", "guards_ok", "slot", "phase", "cost", "lb"),
    "chain": ("count", "unit_count", "guards_ok", "slot", "cls",
              "length"),
    "calendar": ("count", "resv_count", "progress_ok", "served",
                 "level_count"),
}


class TestOnOffBitIdentity:
    # heavy fast-path cells are slow-marked for the tier-1 wall
    # budget (scripts/run_tests.sh runs the full matrix; the ci.sh
    # telemetry smoke gates prefix + bucketed-calendar cheaply)
    @pytest.mark.parametrize("name", [
        "prefix-sort", "prefix-tag32", "prefix-window", "chain",
        pytest.param("prefix-radix", marks=pytest.mark.slow),
        pytest.param("calendar-minstop", marks=pytest.mark.slow),
        pytest.param("calendar-bucketed", marks=pytest.mark.slow),
        pytest.param("calendar-tag32", marks=pytest.mark.slow),
    ])
    def test_decisions_identical_with_telemetry(self, name):
        run = ENGINE_RUNS[name]
        now = jnp.int64(1 * S)
        ep_off = run(_mixed_state(), now)
        ep_on = run(_mixed_state(), now, **_kit(64))
        fields = _DEC_FIELDS[name.split("-")[0]]
        for f in fields:
            assert bool(jnp.array_equal(getattr(ep_off, f),
                                        getattr(ep_on, f))), \
                f"{name}: field {f} diverged with telemetry on"
        assert_states_equal(ep_off.state, ep_on.state)
        assert bool(jnp.array_equal(ep_off.metrics, ep_on.metrics))
        # off = absent, not zeros
        assert ep_off.hists is None and ep_off.ledger is None \
            and ep_off.flight is None

    @pytest.mark.parametrize("name", [
        "prefix-sort", "chain", "calendar-minstop",
        pytest.param("prefix-radix", marks=pytest.mark.slow),
        pytest.param("prefix-tag32", marks=pytest.mark.slow),
        pytest.param("prefix-window", marks=pytest.mark.slow),
        pytest.param("calendar-bucketed", marks=pytest.mark.slow),
        pytest.param("calendar-tag32", marks=pytest.mark.slow),
    ])
    def test_ledger_totals_match_stream(self, name):
        run = ENGINE_RUNS[name]
        ep = run(_mixed_state(), jnp.int64(1 * S), **_kit(64))
        led = np.asarray(jax.device_get(ep.ledger))
        total = int(np.asarray(jax.device_get(ep.count)).sum())
        assert led[:, obshist.LED_OPS].sum() == total
        d = obshist.hist_dict(ep.hists)
        # every committed entry head observed exactly once, in exactly
        # one of the two latency families; at chain_depth=1 every
        # decision IS an entry head, so the counts cover the stream
        if name.startswith("prefix"):
            assert d["decision_latency_ns"]["count"] \
                + d["resv_tardiness_ns"]["count"] == total
        # commit-size sum over batches/levels == total decisions
        assert d["commit_size"]["sum"] == total
        # flight seq advanced iff work committed (calendar-tag32
        # legitimately trips its window on this fixture and commits 0;
        # a gated batch must record nothing)
        assert (int(jax.device_get(ep.flight.seq)) > 0) == (total > 0)


class TestCrossImplEquality:
    def _tele(self, ep):
        return (np.asarray(jax.device_get(ep.hists)),
                np.asarray(jax.device_get(ep.ledger)))

    @pytest.mark.slow
    def test_sort_vs_radix(self):
        now = jnp.int64(1 * S)
        eps = [scan_prefix_epoch(_mixed_state(), now, 3, 4,
                                 anticipation_ns=0, select_impl=impl,
                                 hists=obshist.hist_zero(),
                                 ledger=obshist.ledger_zero(64))
               for impl in ("sort", "radix")]
        ha, la = self._tele(eps[0])
        hb, lb = self._tele(eps[1])
        assert np.array_equal(ha, hb)
        assert np.array_equal(la, lb)

    @pytest.mark.slow
    def test_tag32_vs_int64(self):
        # high-rate QoS (~1e6 ns/serve tag advance): the whole epoch
        # stays inside the +-2^31 ns window (the test_radix fixture)
        infos = {c: ClientInfo(2000, 1000 * (1 + c % 3), 0)
                 for c in range(12)}
        now = jnp.int64(4 * S)
        eps = [scan_prefix_epoch(deep_state(infos, 6), now, 3, 4,
                                 anticipation_ns=0, tag_width=w,
                                 hists=obshist.hist_zero(),
                                 ledger=obshist.ledger_zero(64))
               for w in (64, 32)]
        assert bool(jax.device_get(eps[1].guards_ok).all())
        ha, la = self._tele(eps[0])
        hb, lb = self._tele(eps[1])
        assert np.array_equal(ha, hb)
        assert np.array_equal(la, lb)

    def test_bucketed_l1_bitwise_minstop(self):
        now = jnp.int64(1 * S)
        kw = dict(steps=4, use_pallas=False,
                  hists=obshist.hist_zero(),
                  ledger=obshist.ledger_zero(64))
        a = scan_calendar_epoch(_mixed_state(), now, 2,
                                calendar_impl="minstop", **kw)
        b = scan_calendar_epoch(_mixed_state(), now, 2,
                                calendar_impl="bucketed",
                                ladder_levels=1, **kw)
        ha, la = self._tele(a)
        hb, lb = self._tele(b)
        assert np.array_equal(ha, hb)
        assert np.array_equal(la, lb)

    def test_bucketed_equals_minstop_composition(self):
        """m=1 bucketed epoch at L levels == m=L minstop epoch: each
        ladder level starts from the exact serial state one minstop
        batch would leave, so the per-level telemetry observations
        compose identically."""
        now = jnp.int64(1 * S)
        kw = dict(steps=4, use_pallas=False)
        a = scan_calendar_epoch(_mixed_state(), now, 3,
                                calendar_impl="minstop",
                                hists=obshist.hist_zero(),
                                ledger=obshist.ledger_zero(64), **kw)
        b = scan_calendar_epoch(_mixed_state(), now, 1,
                                calendar_impl="bucketed",
                                ladder_levels=3,
                                hists=obshist.hist_zero(),
                                ledger=obshist.ledger_zero(64), **kw)
        assert int(jax.device_get(a.count).sum()) \
            == int(jax.device_get(b.count).sum())
        ha, la = self._tele(a)
        hb, lb = self._tele(b)
        assert np.array_equal(ha, hb)
        assert np.array_equal(la, lb)
        assert_states_equal(a.state, b.state)


# ----------------------------------------------------------------------
# ledger == host recomputation (device truth)
# ----------------------------------------------------------------------

def _zipf_cfg4_state(n=512, ring=16, depth=8):
    """cfg4-flavored seeded population: Zipf weights + uniform
    reservations, both phases active (the bench workload in
    miniature)."""
    from __graft_entry__ import _preloaded_state

    st = _preloaded_state(n, depth, ring=ring)
    w = np.clip(1.0 / np.arange(1, n + 1) ** 1.1
                / (1.0 / (n // 2) ** 1.1), 0.5, 64.0)
    rng = np.random.default_rng(7)
    rng.shuffle(w)
    winv = np.asarray([rate_to_inv_ns(x) for x in w], np.int64)
    # reservation floor sized so the constraint phase takes PART of
    # service over the test's ~3e8 ns window (rate 10/s -> ~3 of the
    # 8-deep backlog per client), leaving real weight-phase serves
    rinv = np.full(n, rate_to_inv_ns(10.0), dtype=np.int64)
    return st._replace(weight_inv=jnp.asarray(winv),
                       head_prop=jnp.asarray(winv),
                       resv_inv=jnp.asarray(rinv),
                       head_resv=jnp.asarray(rinv))


class TestLedgerDeviceTruth:
    def test_prefix_ledger_equals_host_recount(self):
        """The full decision stream (slot/phase/lb per batch) is the
        host-side ground truth; the ledger must reproduce it
        exactly."""
        st = _mixed_state(depth=8)
        ep = scan_prefix_epoch(st, jnp.int64(1 * S), 4, 4,
                               anticipation_ns=0,
                               allow_limit_break=True,
                               ledger=obshist.ledger_zero(64))
        led = np.asarray(jax.device_get(ep.ledger))
        slot = np.asarray(jax.device_get(ep.slot)).ravel()
        phase = np.asarray(jax.device_get(ep.phase)).ravel()
        lb = np.asarray(jax.device_get(ep.lb)).ravel()
        ops = np.zeros(64, dtype=np.int64)
        resv = np.zeros(64, dtype=np.int64)
        lbs = np.zeros(64, dtype=np.int64)
        ok = slot >= 0
        np.add.at(ops, slot[ok], 1)
        np.add.at(resv, slot[ok & (phase == 0)], 1)
        np.add.at(lbs, slot[ok & lb], 1)
        assert np.array_equal(led[:, obshist.LED_OPS], ops)
        assert np.array_equal(led[:, obshist.LED_RESV_OPS], resv)
        assert np.array_equal(led[:, obshist.LED_LIMIT_BREAKS], lbs)

    def test_cfg4_calendar_ledger_equals_served_accumulation(self):
        """Seeded cfg4-flavored run, accumulators chained across
        epochs on device: the ledger's ops column == the host-summed
        per-epoch served vectors, and the phase totals match the
        metrics vector."""
        st = _zipf_cfg4_state()
        hists = obshist.hist_zero()
        ledger = obshist.ledger_zero(512)
        served_host = np.zeros(512, dtype=np.int64)
        resv_total = 0
        now = 0
        run = jax.jit(functools.partial(
            scan_calendar_epoch, m=2, steps=6, use_pallas=False,
            with_metrics=True, calendar_impl="bucketed",
            ladder_levels=2))
        met = np.zeros(obsdev.NUM_METRICS, dtype=np.int64)
        for _ in range(3):
            now += 10 ** 8
            ep = run(st, jnp.int64(now), hists=hists, ledger=ledger)
            st, hists, ledger = ep.state, ep.hists, ep.ledger
            served_host += np.asarray(jax.device_get(ep.served))
            resv_total += int(jax.device_get(ep.resv_count).sum())
            met = obsdev.metrics_combine_np(
                met, jax.device_get(ep.metrics))
        led = np.asarray(jax.device_get(ledger))
        assert np.array_equal(led[:, obshist.LED_OPS], served_host)
        assert led[:, obshist.LED_RESV_OPS].sum() == resv_total
        assert led[:, obshist.LED_OPS].sum() \
            == met[obsdev.MET_DECISIONS]
        assert led[:, obshist.LED_RESV_OPS].sum() \
            == met[obsdev.MET_RESV]
        # both phases genuinely active in the fixture
        assert 0 < resv_total < int(served_host.sum())
        # tardiness columns populated and self-consistent
        assert (led[:, obshist.LED_TARD_MAX]
                <= led[:, obshist.LED_TARD_SUM]).all()


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------

class TestFlightRecorder:
    def test_wraparound_keeps_newest(self):
        fl = obsflight.flight_init(8)
        for b in range(4):
            slot = jnp.asarray([b * 3, b * 3 + 1, b * 3 + 2],
                               dtype=jnp.int32)
            fl = obsflight.flight_record(
                fl, slot, jnp.zeros(3, jnp.int64),
                jnp.full(3, b, jnp.int64), jnp.ones(3, jnp.int64))
        assert int(jax.device_get(fl.seq)) == 12
        assert int(jax.device_get(fl.batch)) == 4
        recs = obsflight.flight_drain(fl)
        assert len(recs) == 8
        assert [r["seq"] for r in recs] == list(range(4, 12))
        assert recs[-1]["client"] == 11 and recs[-1]["batch"] == 3

    def test_one_batch_overflow_deterministic(self):
        fl = obsflight.flight_init(4)
        slot = jnp.arange(10, dtype=jnp.int32)
        fl = obsflight.flight_record(
            fl, slot, jnp.zeros(10, jnp.int64),
            jnp.arange(10, dtype=jnp.int64) * 7,
            jnp.ones(10, jnp.int64))
        assert int(jax.device_get(fl.seq)) == 10
        recs = obsflight.flight_drain(fl)
        assert [r["seq"] for r in recs] == [6, 7, 8, 9]
        assert [r["client"] for r in recs] == [6, 7, 8, 9]

    def test_masked_and_dead_batches_write_nothing(self):
        fl = obsflight.flight_init(8)
        none = jnp.full(4, -1, dtype=jnp.int32)
        z = jnp.zeros(4, jnp.int64)
        fl = obsflight.flight_record(fl, none, z, z, z)
        assert int(jax.device_get(fl.seq)) == 0
        assert int(jax.device_get(fl.batch)) == 1  # live, 0 records
        fl = obsflight.flight_record(
            fl, jnp.arange(4, dtype=jnp.int32), z, z, z,
            live=jnp.bool_(False))
        assert int(jax.device_get(fl.seq)) == 0    # dead: gated out
        assert int(jax.device_get(fl.batch)) == 1
        assert obsflight.flight_drain(fl) == []

    def test_scattered_mask_ranks(self):
        fl = obsflight.flight_init(8)
        slot = jnp.asarray([-1, 5, -1, 9, -1, 2], dtype=jnp.int32)
        fl = obsflight.flight_record(
            fl, slot, jnp.zeros(6, jnp.int64),
            jnp.zeros(6, jnp.int64), jnp.ones(6, jnp.int64))
        recs = obsflight.flight_drain(fl)
        assert [r["client"] for r in recs] == [5, 9, 2]
        assert [r["seq"] for r in recs] == [0, 1, 2]

    def test_dump_round_trip(self, tmp_path):
        fl = obsflight.flight_init(4)
        fl = obsflight.flight_record(
            fl, jnp.asarray([1, 2], jnp.int32),
            jnp.asarray([0, 1], jnp.int64),
            jnp.asarray([10, 20], jnp.int64),
            jnp.asarray([1, 3], jnp.int64))
        p = tmp_path / "flight.jsonl"
        n = obsflight.flight_dump(fl, str(p))
        rows = [json.loads(l) for l in p.read_text().splitlines()]
        assert n == len(rows) == 2
        assert rows[1] == {"seq": 1, "batch": 0, "client": 2,
                           "cls": 1, "tag": 20, "cost": 3,
                           "margin": -1, "gate": 0}

    def test_epoch_flight_matches_stream(self):
        """Prefix-epoch flight records ARE the decision stream's tail
        (client/cost per committed decision, in commit order)."""
        ep = scan_prefix_epoch(_mixed_state(), jnp.int64(1 * S), 3, 4,
                               anticipation_ns=0,
                               flight=obsflight.flight_init(256))
        slot = np.asarray(jax.device_get(ep.slot)).ravel()
        cost = np.asarray(jax.device_get(ep.cost)).ravel()
        ok = slot >= 0
        recs = obsflight.flight_drain(ep.flight)
        assert [r["client"] for r in recs] == slot[ok].tolist()
        assert [r["cost"] for r in recs] == cost[ok].tolist()
        assert int(jax.device_get(ep.flight.seq)) == int(ok.sum())


# ----------------------------------------------------------------------
# mesh merge (the psum/pmax collective path)
# ----------------------------------------------------------------------

class TestMeshReduce:
    def test_hist_and_ledger_mesh_reduce(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 (virtual) devices")
        from jax.sharding import Mesh, PartitionSpec as P

        from dmclock_tpu.utils.compat import shard_map

        mesh = Mesh(np.array(jax.devices()[:4]), ("servers",))
        hs = jnp.stack([obshist.hist_zero().at[0, i].add(i + 1)
                        for i in range(4)])
        ls = jnp.stack([
            obshist.ledger_zero(5).at[0].set(jnp.asarray(
                [i, 0, 0, 10 * i, 10 * i], dtype=jnp.int64))
            for i in range(4)])

        def merge(h, l):
            return (obshist.hist_mesh_reduce(h[0], "servers"),
                    obshist.ledger_mesh_reduce(l[0], "servers"))

        mh, ml = shard_map(
            merge, mesh=mesh,
            in_specs=(P("servers"), P("servers")),
            out_specs=(P(), P()))(hs, ls)
        want_h = np.asarray(jax.device_get(hs)).sum(axis=0)
        assert np.array_equal(np.asarray(jax.device_get(mh)), want_h)
        ml = np.asarray(jax.device_get(ml))
        assert ml[0, obshist.LED_OPS] == 0 + 1 + 2 + 3
        assert ml[0, obshist.LED_TARD_SUM] == 60      # psum
        assert ml[0, obshist.LED_TARD_MAX] == 30      # pmax


# ----------------------------------------------------------------------
# guarded runner pass-through
# ----------------------------------------------------------------------

class TestGuardedTelemetry:
    def test_guarded_matches_bare_epoch(self):
        st = _mixed_state()
        now = 1 * S
        bare = scan_prefix_epoch(st, jnp.int64(now), 3, 4,
                                 anticipation_ns=0,
                                 hists=obshist.hist_zero(),
                                 ledger=obshist.ledger_zero(64),
                                 flight=obsflight.flight_init(32))
        ep = run_epoch_guarded(st, now, engine="prefix", m=3, k=4,
                               hists=obshist.hist_zero(),
                               ledger=obshist.ledger_zero(64),
                               flight=obsflight.flight_init(32))
        assert np.array_equal(np.asarray(jax.device_get(bare.hists)),
                              np.asarray(jax.device_get(ep.hists)))
        assert np.array_equal(np.asarray(jax.device_get(bare.ledger)),
                              np.asarray(jax.device_get(ep.ledger)))
        assert np.array_equal(
            np.asarray(jax.device_get(bare.flight.buf)),
            np.asarray(jax.device_get(ep.flight.buf)))

    def test_tag32_window_trip_resume_accumulates(self):
        """A deterministic tag32 window trip: the int64 resume must
        CONTINUE the accumulators, so the final ledger still equals
        the guarded run's total committed count."""
        st = _mixed_state()
        st = st._replace(head_prop=st.head_prop.at[0]
                         .add(jnp.int64(1) << 40))
        ep = run_epoch_guarded(st, 1 * S, engine="prefix", m=3, k=4,
                               tag_width=32,
                               ledger=obshist.ledger_zero(64))
        assert ep.rebase_fallbacks == 1
        led = np.asarray(jax.device_get(ep.ledger))
        assert led[:, obshist.LED_OPS].sum() == ep.count


# ----------------------------------------------------------------------
# queue host-ledger mirror
# ----------------------------------------------------------------------

class TestQueueLedger:
    def test_pull_queue_ledger_matches_counters(self):
        from dmclock_tpu.core.recs import ReqParams
        from dmclock_tpu.engine import TpuPullPriorityQueue

        q = TpuPullPriorityQueue(lambda c: INFOS[c], capacity=8,
                                 ring_capacity=8)
        t = 1 * S
        for i in range(6):
            q.add_request(("r", i), i % 2, ReqParams(1, 1),
                          time_ns=t, cost=1)
        served = 0
        for _ in range(6):
            pr = q.pull_request(now_ns=t + served * 10)
            if pr.is_retn():
                served += 1
        rows = q.ledger_rows()
        assert sum(int(r[0]) for r in rows.values()) == served \
            == q.reserv_sched_count + q.prop_sched_count
        assert sum(int(r[1]) for r in rows.values()) \
            == q.reserv_sched_count
        # tardiness columns stay zero on the host mirror (documented)
        assert all(int(r[3]) == 0 and int(r[4]) == 0
                   for r in rows.values())

    @pytest.mark.slow
    def test_sim_ledger_check_cross_checks(self):
        from dmclock_tpu.sim import ClientGroup, ServerGroup, SimConfig
        from dmclock_tpu.sim.dmc_sim import run_sim

        cfg = SimConfig(
            client_groups=1, server_groups=1,
            cli_group=[ClientGroup(
                client_count=2, client_total_ops=30,
                client_iops_goal=80.0, client_reservation=20.0,
                client_limit=100.0, client_weight=1.0,
                client_outstanding_ops=8,
                client_server_select_range=1)],
            srv_group=[ServerGroup(server_count=1, server_iops=200.0,
                                   server_threads=2)])
        sim = run_sim(cfg, model="dmclock-tpu", seed=3)
        chk = sim.report().ledger_check()
        assert chk is not None
        assert chk["mismatches"] == []
        assert chk["ops"] == 2 * 30
        # the oracle model has no backend ledger -> None path
        sim2 = run_sim(cfg, model="dmclock", seed=3)
        assert sim2.report().ledger_check() is None
        # ...but DOES materialize tags -> host tardiness percentiles
        pct = sim2.report().tardiness_percentiles()
        assert pct is not None and pct["count"] > 0
        rows = sim2.report().conformance()
        assert any(r["tardiness_max_ns"] >= 0 for r in rows)


# ----------------------------------------------------------------------
# registry export + healthz
# ----------------------------------------------------------------------

class TestRegistryExport:
    def test_publish_hists_prometheus_families(self):
        reg = MetricsRegistry()
        h = obshist.hist_zero()
        h = obshist.hist_observe(
            h, obshist.HIST_RESV_TARDINESS,
            jnp.asarray([1, 5, 1000], dtype=jnp.int64),
            jnp.ones(3, dtype=bool))
        obshist.publish_hists(reg, h, prefix="dmclock")
        text = reg.prometheus()
        assert "# TYPE dmclock_resv_tardiness_ns histogram" in text
        assert 'dmclock_resv_tardiness_ns_bucket{le="1"} 1' in text
        assert 'dmclock_resv_tardiness_ns_bucket{le="+Inf"} 3' in text
        assert "dmclock_resv_tardiness_ns_sum 1006" in text
        assert "dmclock_resv_tardiness_ns_count 3" in text
        # publish is a SET drain: re-publishing must not double-count
        obshist.publish_hists(reg, h, prefix="dmclock")
        assert "dmclock_resv_tardiness_ns_count 3" \
            in reg.prometheus()

    def test_publish_ledger_totals(self):
        reg = MetricsRegistry()
        led = obshist.ledger_zero(4).at[1].set(
            jnp.asarray([7, 3, 1, 90, 60], dtype=jnp.int64))
        obshist.publish_ledger(reg, led)
        snap = reg.snapshot()
        assert snap["dmclock_ledger_ops"][0]["value"] == 7
        assert snap["dmclock_ledger_tardiness_max_ns"][0]["value"] \
            == 60

    def test_healthz_endpoint(self):
        import urllib.request

        from dmclock_tpu.obs import MetricsHTTPServer

        reg = MetricsRegistry()
        with MetricsHTTPServer(reg, port=0) as srv:
            with urllib.request.urlopen(srv.healthz_url,
                                        timeout=5) as resp:
                assert resp.status == 200
                assert json.loads(resp.read()) == {"status": "ok"}

    def test_supervisor_healthz_probe(self):
        from dmclock_tpu.obs import MetricsHTTPServer
        from dmclock_tpu.robust.supervisor import _healthz_ok

        with MetricsHTTPServer(MetricsRegistry(), port=0) as srv:
            assert _healthz_ok(srv)
        assert not _healthz_ok(srv)      # closed server fails fast
