"""Differential tests for the calendar-commit (sortless) engine.

``calendar_batch`` promises: the committed SET -- per-client decision
/ constraint-phase / limit-break counts -- and the final state are
EXACTLY the serial engine's after ``count`` decisions, for the batch's
computed boundary B_eff.  Split from test_prefix.py: one pytest
process holding both suites' compiled programs exceeds this box's
XLA-CPU memory tolerance (see conftest).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import kernels

from engine_helpers import (assert_states_equal, build_state,
                            deep_state)
from test_prefix import mixed_qos_state, serial_run_lb

S = NS_PER_SEC


def check_calendar_vs_serial(state, now, steps, *, allow=False,
                             anticipation_ns=0):
    """One calendar batch vs the serial engine run for `count` steps:
    the committed SET (per-client decision/phase/limit-break counts)
    and the final state must match exactly."""
    from dmclock_tpu.engine.fastpath import calendar_batch

    b = calendar_batch(state, jnp.int64(now), steps=steps,
                       anticipation_ns=anticipation_ns,
                       allow_limit_break=allow)
    assert bool(b.progress_ok)
    c = int(b.count)
    if c == 0:
        assert_states_equal(b.state, state)
        _, ser = serial_run_lb(state, now, 1, allow)
        assert ser.type[0] != kernels.RETURNING, \
            "calendar committed 0 but serial engine would serve"
        return b.state, 0
    ser_state, ser = serial_run_lb(state, now, c, allow)
    assert (ser.type == kernels.RETURNING).all()
    n = state.capacity
    served = np.zeros(n, np.int32)
    np.add.at(served, ser.slot, 1)
    assert np.array_equal(served, jax.device_get(b.served)), \
        "per-client decision counts diverge"
    resv = np.zeros(n, np.int32)
    np.add.at(resv, ser.slot[ser.phase == 0], 1)
    assert np.array_equal(resv, jax.device_get(b.served_resv)), \
        "per-client constraint-phase counts diverge"
    lbc = np.zeros(n, np.int32)
    np.add.at(lbc, ser.slot[ser.limit_break], 1)
    assert np.array_equal(lbc, jax.device_get(b.lb)), \
        "per-client limit-break counts diverge"
    assert_states_equal(b.state, ser_state)
    return b.state, c


def drive_calendar(state, now, steps, *, allow=False,
                   anticipation_ns=0, max_batches=300):
    counts = []
    st = state
    for _ in range(max_batches):
        st, c = check_calendar_vs_serial(
            st, now, steps, allow=allow,
            anticipation_ns=anticipation_ns)
        counts.append(c)
        if c == 0:
            break
    return st, counts


@pytest.mark.slow
def test_calendar_weight_steady_state():
    """Pure weight workload: every client commits up to `steps`
    decisions per batch (the sort-based batch is capped at one serve
    per client per sorted window)."""
    infos = {c: ClientInfo(0, 1 + (c % 4), 0) for c in range(10)}
    state = deep_state(infos, depth=24)
    st, counts = drive_calendar(state, 60 * S, 8)
    assert sum(counts) == 10 * 24
    assert max(counts) > 20, f"calendar never batched deep: {counts}"


@pytest.mark.slow
def test_calendar_heavy_weight_skew():
    """The cfg4 cutter shape: one weight-64 client among weight-1
    clients.  A sort batch commits only the entries inside the heavy
    client's 2*winv re-entry window; the calendar batch must follow
    the heavy client many serves deep in ONE pass."""
    infos = {0: ClientInfo(0, 64, 0)}
    for c in range(1, 9):
        infos[c] = ClientInfo(0, 1, 0)
    state = deep_state(infos, depth=32)
    from dmclock_tpu.engine.fastpath import calendar_batch
    b = calendar_batch(state, jnp.int64(500 * S), steps=16,
                       anticipation_ns=0)
    assert int(jax.device_get(b.served)[0]) > 8, \
        "heavy client not followed deep"
    check_calendar_vs_serial(state, 500 * S, 16)


@pytest.mark.slow
def test_calendar_mixed_regimes():
    state, now = mixed_qos_state(n=8, depth=12)
    st, counts = drive_calendar(state, now, 8)
    assert sum(counts) == 8 * 12


def test_calendar_resv_arrears():
    """Deep reservation arrears (the cfg4 round-start segment) commit
    across many serves per client in one batch."""
    infos = {c: ClientInfo(2, 1, 0) for c in range(8)}
    state = deep_state(infos, depth=16)
    st, counts = drive_calendar(state, 9 * S, 16)
    assert sum(counts) == 8 * 16
    assert max(counts) > 30


def test_calendar_single_client():
    infos = {0: ClientInfo(0, 1, 0)}
    adds = [(0, 1 * S, 1, 1, 1) for _ in range(20)]
    state = build_state(infos, adds, capacity=8, ring=32)
    st, counts = drive_calendar(state, 100 * S, 16)
    assert sum(counts) == 20
    assert counts[0] >= 15, f"single client not followed: {counts}"


def test_calendar_nothing_eligible():
    infos = {c: ClientInfo(5, 0, 0) for c in range(4)}
    adds = [(c, 100 * S, 1, 1, 1) for c in range(4)]
    state = build_state(infos, adds, capacity=8)
    check_calendar_vs_serial(state, 1, 4)


# the fuzz families are slow (scripts/run_tests.sh still runs them):
# each seed costs ~90s on the CPU box and the suite outgrew the
# tier-1 wall budget at PR-9; the named differential tests above keep
# the quick sweep's calendar-vs-serial coverage
@pytest.mark.slow
@pytest.mark.parametrize("seed", [61, 62, 63, 64, 65])
def test_fuzz_calendar_matches_serial(seed):
    """Random QoS mixes / costs / arrivals: calendar batches replay
    the serial engine exactly (set + state), Wait mode."""
    rng = random.Random(seed)
    n = rng.randint(2, 16)
    infos = {}
    for c in range(n):
        kind = rng.randrange(4)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 3), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4),
                                  rng.uniform(4, 9))
        else:
            infos[c] = ClientInfo(rng.uniform(0.5, 3),
                                  rng.uniform(0.5, 3), 0)
    adds = []
    t = 1 * S
    for _ in range(rng.randint(20, 150)):
        c = rng.randrange(n)
        t += rng.randint(0, S // 4)
        delta = rng.randint(1, 5)
        adds.append((c, t, rng.randint(1, 3), delta,
                     rng.randint(1, delta)))
    state = build_state(infos, adds, capacity=32)
    steps = rng.choice([4, 8])
    now = t + rng.randint(0, 6) * S
    st = state
    for _ in range(14):
        st, c = check_calendar_vs_serial(st, now, steps)
        if c == 0:
            now += rng.randint(1, 5) * S


@pytest.mark.slow
@pytest.mark.parametrize("seed", [71, 72, 73])
def test_fuzz_calendar_allow(seed):
    """Allow mode (weights > 0 everywhere): calendar batches replay
    the serial limit-break engine exactly."""
    rng = random.Random(seed)
    n = rng.randint(3, 12)
    infos = {c: ClientInfo(rng.choice([0, 0.5, 1.0]),
                           rng.uniform(0.5, 3),
                           rng.choice([0, 2.0, 4.0]))
             for c in range(n)}
    state = deep_state(infos, depth=rng.randint(2, 8), capacity=16)
    now = rng.randint(1, 8) * S
    st = state
    for _ in range(12):
        st, c = check_calendar_vs_serial(st, now, rng.choice([4, 8]),
                                         allow=True)
        if c == 0:
            now += rng.randint(1, 4) * S


@pytest.mark.slow
def test_calendar_anticipation():
    rng = random.Random(23)
    ant = S // 2
    infos = {c: ClientInfo(0, 1.0 + c % 3, 0) for c in range(8)}
    adds = []
    t = S
    for i in range(80):
        c = rng.randrange(8)
        t += rng.choice([ant // 4, ant // 3, 2 * ant])
        adds.append((c, t, rng.randint(1, 3), rng.randint(1, 4), 1))
    state = build_state(infos, adds, capacity=16, ring=32,
                        anticipation_ns=ant)
    st, counts = drive_calendar(state, t + 1000 * S, 8,
                                anticipation_ns=ant)
    assert sum(counts) == 80


@pytest.mark.slow
def test_calendar_epoch_matches_batches():
    from dmclock_tpu.engine.fastpath import (calendar_batch,
                                             scan_calendar_epoch)

    state, now = mixed_qos_state(n=8, depth=10)
    m, steps = 5, 6
    ep = scan_calendar_epoch(state, jnp.int64(now), m, steps=steps,
                             anticipation_ns=0)
    assert bool(jax.device_get(ep.progress_ok).all())
    st = state
    total_served = np.zeros(state.capacity, np.int32)
    for i in range(m):
        b = calendar_batch(st, jnp.int64(now), steps=steps,
                           anticipation_ns=0)
        assert int(b.count) == int(jax.device_get(ep.count)[i])
        assert int(b.resv_count) == \
            int(jax.device_get(ep.resv_count)[i])
        total_served += jax.device_get(b.served)
        st = b.state
    assert np.array_equal(total_served, jax.device_get(ep.served))
    assert_states_equal(ep.state, st)
