"""Differential tests for the bucketed calendar ladder.

``calendar_batch_bucketed`` promises: the committed SET (per-client
decision / constraint-phase / limit-break counts) and the final state
are EXACTLY the serial engine's after ``count`` decisions -- the same
contract as the minstop ``calendar_batch`` (test_calendar.py), with L
fused refreshed-budget boundaries per launch instead of one.  The
zero-ladder configuration (levels=1) must be BIT-identical to the
minstop path, and the epoch/device-sim/metrics plumbing must be
invisible to the decision stream.

Split from test_calendar.py for the same per-process XLA-CPU memory
reason (conftest).  The compile-heavy shapes (the population/L drive
matrices, the fuzz matrix, tag32, the sharded device-sim parity)
carry ``@pytest.mark.slow``: the quick tier-1 sweep (-m 'not slow')
keeps the acceptance pins -- L=1 bitwise identity, mid-ladder budget
refresh vs serial, commits-more-per-launch, quantile planner, metrics
bit-identity -- and scripts/run_tests.sh (CI) runs everything.
"""

import functools
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmclock_tpu.core import ClientInfo
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.engine import kernels

from engine_helpers import (assert_states_equal, build_state,
                            deep_state)
from test_prefix import mixed_qos_state, serial_run_lb

S = NS_PER_SEC

# module-level jit cache: the drive loops call the same (steps,
# levels, allow) config many times, and an un-jitted call re-traces
# and re-compiles the whole L-level scan every time
_JIT: dict = {}


def ladder_batch(state, now, steps, levels, *, allow=False,
                 anticipation_ns=0):
    from dmclock_tpu.engine.fastpath import calendar_batch_bucketed

    key = ("ladder", state.capacity, state.ring_capacity, steps,
           levels, allow, anticipation_ns)
    if key not in _JIT:
        _JIT[key] = jax.jit(functools.partial(
            calendar_batch_bucketed, steps=steps, levels=levels,
            anticipation_ns=anticipation_ns, allow_limit_break=allow))
    return _JIT[key](state, jnp.int64(now))


def minstop_batch(state, now, steps):
    from dmclock_tpu.engine.fastpath import calendar_batch

    key = ("minstop", state.capacity, state.ring_capacity, steps)
    if key not in _JIT:
        _JIT[key] = jax.jit(functools.partial(calendar_batch,
                                              steps=steps))
    return _JIT[key](state, jnp.int64(now))


def check_ladder_vs_serial(state, now, steps, levels, *, allow=False,
                           anticipation_ns=0):
    """One bucketed batch vs the serial engine run for `count` steps:
    committed SET (per-client decision/phase/limit-break counts) and
    final state must match exactly."""
    b = ladder_batch(state, now, steps, levels, allow=allow,
                     anticipation_ns=anticipation_ns)
    c = int(b.count)
    assert c == int(np.asarray(b.level_count).sum())
    if c == 0:
        assert_states_equal(b.state, state)
        _, ser = serial_run_lb(state, now, 1, allow)
        if bool(b.progress_ok):
            assert ser.type[0] != kernels.RETURNING, \
                "ladder committed 0 but serial engine would serve"
        return b.state, 0
    ser_state, ser = serial_run_lb(state, now, c, allow)
    assert (ser.type == kernels.RETURNING).all()
    n = state.capacity
    served = np.zeros(n, np.int32)
    np.add.at(served, ser.slot, 1)
    assert np.array_equal(served, jax.device_get(b.served)), \
        "per-client decision counts diverge"
    resv = np.zeros(n, np.int32)
    np.add.at(resv, ser.slot[ser.phase == 0], 1)
    assert np.array_equal(resv, jax.device_get(b.served_resv)), \
        "per-client constraint-phase counts diverge"
    lbc = np.zeros(n, np.int32)
    np.add.at(lbc, ser.slot[ser.limit_break], 1)
    assert np.array_equal(lbc, jax.device_get(b.lb)), \
        "per-client limit-break counts diverge"
    assert_states_equal(b.state, ser_state)
    return b.state, c


def zipf64_state(n=10, depth=32):
    """The cfg4 cutter shape: one weight-64 heavy client among
    weight-1 clients (test_calendar.py's skew, deeper)."""
    infos = {0: ClientInfo(0, 64, 0)}
    for c in range(1, n):
        infos[c] = ClientInfo(0, 1, 0)
    return deep_state(infos, depth=depth)


@pytest.mark.slow
@pytest.mark.parametrize("levels", [1, 2])
def test_ladder_uniform_population(levels):
    """Uniform weights: every client stops at ~the same key, so the
    ladder's levels advance the whole population L slabs per launch."""
    infos = {c: ClientInfo(0, 2, 0) for c in range(8)}
    state = deep_state(infos, depth=24)
    st, c = check_ladder_vs_serial(state, 60 * S, 6, levels)
    assert c > 0
    # drive to drain, every batch exact
    for _ in range(12):
        st, c = check_ladder_vs_serial(st, 60 * S, 6, levels)
        if c == 0:
            break
    assert int(np.asarray(st.depth).sum()) == 0


@pytest.mark.slow
@pytest.mark.parametrize("levels", [2, 8])
def test_ladder_zipf64_population(levels):
    """Zipf-64 skew: the heavy client budget-stops early and truncates
    every minstop batch; the ladder must still be exactly serial."""
    state = zipf64_state(n=10, depth=32)
    st = state
    for _ in range(4):
        st, c = check_ladder_vs_serial(st, 500 * S, 8, levels)
        if c == 0:
            break


def test_ladder_commits_more_per_launch_on_skew():
    """The perf claim at batch granularity: on the Zipf-64 shape a
    4-level ladder commits strictly more decisions in ONE launch than
    the minstop batch (the acceptance-criterion currency)."""
    state = zipf64_state(n=10, depth=32)
    b_min = minstop_batch(state, 500 * S, 8)
    b_lad = ladder_batch(state, 500 * S, 8, 4)
    assert int(b_lad.count) > int(b_min.count), \
        (int(b_lad.count), int(b_min.count))
    assert int(np.asarray(b_lad.level_count)[0]) == int(b_min.count)


def test_ladder_budget_exhaustion_mid_ladder():
    """steps budget exhaustion mid-ladder: a single deep client with
    steps=4 exhausts its budget at EVERY level boundary; each level
    must refresh the budget and continue exactly where it stopped."""
    infos = {0: ClientInfo(0, 1, 0)}
    adds = [(0, 1 * S, 1, 1, 1) for _ in range(20)]
    state = build_state(infos, adds, capacity=8, ring=32)
    b = ladder_batch(state, 100 * S, 4, 3)
    # 3 levels x 4-step budget, 20 queued: every level commits its
    # full budget (the ladder's whole point)
    assert np.array_equal(np.asarray(b.level_count), [4, 4, 4])
    check_ladder_vs_serial(state, 100 * S, 4, 3)


@pytest.mark.slow
def test_ladder_l1_bit_identical_to_minstop():
    """levels=1 must reproduce calendar_batch bit for bit: same
    committed counts, same final state -- the digest-gate contract."""
    for state, now in ((zipf64_state(n=8, depth=16), 500 * S),
                       mixed_qos_state(n=8, depth=10)):
        st_m, st_l = state, state
        for _ in range(3):
            bm = minstop_batch(st_m, now, 6)
            bl = ladder_batch(st_l, now, 6, 1)
            assert int(bm.count) == int(bl.count)
            for f in ("units", "served", "served_resv", "lb"):
                assert np.array_equal(jax.device_get(getattr(bm, f)),
                                      jax.device_get(getattr(bl, f))), f
            assert bool(bm.progress_ok) == bool(bl.progress_ok)
            assert_states_equal(bm.state, bl.state)
            st_m, st_l = bm.state, bl.state


@pytest.mark.slow
def test_ladder_mixed_regimes_and_allow():
    """Interleaved constraint/weight regimes and the AtLimit::Allow
    third class ride the ladder exactly."""
    state, now = mixed_qos_state(n=8, depth=12)
    st = state
    for _ in range(4):
        st, c = check_ladder_vs_serial(st, now, 6, 3)
        if c == 0:
            break
    rng = random.Random(77)
    infos = {c: ClientInfo(rng.choice([0, 0.5, 1.0]),
                           rng.uniform(0.5, 3),
                           rng.choice([0, 2.0, 4.0]))
             for c in range(8)}
    state = deep_state(infos, depth=6, capacity=16)
    now2 = 4 * S
    st = state
    for _ in range(4):
        st, c = check_ladder_vs_serial(st, now2, 4, 2, allow=True)
        if c == 0:
            now2 += 2 * S


@pytest.mark.slow
@pytest.mark.parametrize("seed", [81, 82, 83])
def test_fuzz_ladder_matches_serial(seed):
    """Random QoS mixes / costs / arrivals under random ladder depths:
    bucketed batches replay the serial engine exactly."""
    rng = random.Random(seed)
    n = rng.randint(2, 12)
    infos = {}
    for c in range(n):
        kind = rng.randrange(4)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 3), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4),
                                  rng.uniform(4, 9))
        else:
            infos[c] = ClientInfo(rng.uniform(0.5, 3),
                                  rng.uniform(0.5, 3), 0)
    adds = []
    t = 1 * S
    for _ in range(rng.randint(20, 100)):
        c = rng.randrange(n)
        t += rng.randint(0, S // 4)
        delta = rng.randint(1, 5)
        adds.append((c, t, rng.randint(1, 3), delta,
                     rng.randint(1, delta)))
    state = build_state(infos, adds, capacity=16)
    steps, levels = rng.choice([4, 8]), rng.choice([2, 3])
    now = t + rng.randint(0, 6) * S
    st = state
    for _ in range(8):
        st, c = check_ladder_vs_serial(st, now, steps, levels)
        if c == 0:
            now += rng.randint(1, 5) * S


def test_quantile_ladder_matches_numpy():
    """kernels.radix_quantile_ladder == numpy CDF quantiles of the
    finite stop keys (the histogram planner view)."""
    from dmclock_tpu.engine.fastpath import calendar_stop_ladder

    state = zipf64_state(n=12, depth=16)
    lad, stop = calendar_stop_ladder(state, jnp.int64(500 * S),
                                     steps=6, levels=4)
    stop = np.asarray(jax.device_get(stop))
    fin = np.sort(stop[stop < kernels.KEY_INF])
    assert fin.size > 0
    want = fin[[max(int(np.ceil(i * fin.size / 4)), 1) - 1
                for i in (1, 2, 3, 4)]]
    assert np.array_equal(np.asarray(jax.device_get(lad)), want)
    # rank-1 of the histogram walk IS the min (the ladder boundary)
    assert int(kernels.radix_kth_key(jnp.asarray(stop), 1)) \
        == int(fin.min())


@pytest.mark.slow
def test_bucketed_epoch_matches_batches():
    """scan_calendar_epoch(calendar_impl="bucketed") == the sequence
    of calendar_batch_bucketed calls, including per-level counts."""
    from dmclock_tpu.engine.fastpath import scan_calendar_epoch

    state, now = mixed_qos_state(n=8, depth=10)
    m, steps, levels = 4, 6, 2
    ep = scan_calendar_epoch(state, jnp.int64(now), m, steps=steps,
                             anticipation_ns=0,
                             calendar_impl="bucketed",
                             ladder_levels=levels)
    assert ep.level_count.shape == (m, levels)
    st = state
    total_served = np.zeros(state.capacity, np.int32)
    for i in range(m):
        b = ladder_batch(st, now, steps, levels)
        assert int(b.count) == int(jax.device_get(ep.count)[i])
        assert np.array_equal(np.asarray(b.level_count),
                              np.asarray(ep.level_count)[i])
        assert bool(b.progress_ok) == \
            bool(jax.device_get(ep.progress_ok)[i])
        total_served += jax.device_get(b.served)
        st = b.state
    assert np.array_equal(total_served, jax.device_get(ep.served))
    assert_states_equal(ep.state, st)


def test_bucketed_epoch_metrics_identical():
    """with_metrics must be invisible to the bucketed decision stream,
    and the ladder rows must account the levels exactly."""
    from dmclock_tpu.engine.fastpath import scan_calendar_epoch
    from dmclock_tpu.obs import device as obsdev

    state = zipf64_state(n=8, depth=16)
    kw = dict(steps=6, anticipation_ns=0, calendar_impl="bucketed",
              ladder_levels=3)
    now = jnp.int64(500 * S)
    ep_off = scan_calendar_epoch(state, now, 2, **kw)
    ep_on = scan_calendar_epoch(state, now, 2, with_metrics=True,
                                **kw)
    for f in ("count", "resv_count", "progress_ok", "served",
              "level_count"):
        assert bool(jnp.array_equal(getattr(ep_off, f),
                                    getattr(ep_on, f))), \
            f"bucketed epoch field {f} diverged with metrics on"
    assert_states_equal(ep_off.state, ep_on.state)
    m = obsdev.metrics_dict(ep_on.metrics)
    lvls = np.asarray(ep_on.level_count)
    assert m["decisions_total"] == int(lvls.sum())
    assert m["calendar_ladder_levels_used"] == int((lvls > 0).sum())
    assert m["calendar_ladder_base_decisions"] == int(lvls[:, 0].sum())
    assert m["calendar_ladder_fallbacks"] == 0


@pytest.mark.slow
def test_bucketed_epoch_tag32_exact():
    """The int32 tag carry composes with the bucketed path: on a
    window-fitting (high-rate) state tag_width=32 must be
    bit-identical to tag_width=64."""
    from dmclock_tpu.engine.fastpath import scan_calendar_epoch

    infos = {c: ClientInfo(0, 1000.0 + 500 * (c % 3), 0)
             for c in range(6)}
    state = deep_state(infos, depth=12)
    kw = dict(steps=4, anticipation_ns=0, calendar_impl="bucketed",
              ladder_levels=2)
    now = jnp.int64(2 * S)
    e64 = scan_calendar_epoch(state, now, 2, tag_width=64, **kw)
    e32 = scan_calendar_epoch(state, now, 2, tag_width=32, **kw)
    assert bool(jax.device_get(e32.progress_ok).all()), \
        "tag32 window tripped on the high-rate shape"
    for f in ("count", "resv_count", "progress_ok", "served",
              "level_count"):
        assert bool(jnp.array_equal(getattr(e64, f),
                                    getattr(e32, f))), f
    assert_states_equal(e64.state, e32.state)


# ----------------------------------------------------------------------
# device_sim plumbing: the calendar serve path is invisible to service
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh8():
    from dmclock_tpu.sim import device_sim as DS

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return DS.make_mesh(8)


@pytest.mark.slow
def test_device_sim_calendar_serve_parity(mesh8):
    """DeviceSimSpec.calendar_impl front-loads slices with sortless
    calendar batches; service must be EXACTLY the default path's
    (both are the q-step serial stream), for minstop and bucketed --
    the full DeviceSim pytree must match.

    (Historical note pinning the boundary-read choice: with the ladder
    boundary computed through the dense-histogram walk instead of the
    equal-valued ``jnp.min``, THIS program -- the ladder under the
    8-shard shard_map sim -- deterministically SIGFPE'd this stack's
    XLA:CPU compiler.  The commit boundary therefore reads the first
    order statistic as a plain min; see _calendar_batch_core.)"""
    import dataclasses

    from dmclock_tpu.sim import device_sim as DS
    from dmclock_tpu.sim.config import (ClientGroup, ServerGroup,
                                        SimConfig)

    groups = [ClientGroup(client_count=24, client_total_ops=10 ** 9,
                          client_iops_goal=2000,
                          client_outstanding_ops=60,
                          client_reservation=100.0, client_limit=0.0,
                          client_weight=2.0,
                          client_server_select_range=8)]
    cfg = SimConfig(client_groups=1, server_groups=1,
                    cli_group=groups,
                    srv_group=[ServerGroup(server_count=8,
                                           server_iops=20000.0,
                                           server_threads=1)])
    sim, spec = DS.init_device_sim(cfg)
    outs = {}
    for spc in (spec,
                dataclasses.replace(spec, calendar_impl="minstop"),
                dataclasses.replace(spec, calendar_impl="bucketed",
                                    ladder_levels=3)):
        sm = DS.shard_device_sim(sim, mesh8)
        step = jax.jit(functools.partial(
            DS.device_sim_step, spec=spc, mesh=mesh8, slices=8))
        for _ in range(3):
            sm = step(sm)
        outs[spc.calendar_impl] = jax.block_until_ready(sm)
        # three shard_map sim programs in one process: drop each
        # spec's compiled state before the next (conftest's XLA-CPU
        # footprint note)
        jax.clear_caches()
    base = outs[None]
    for name in ("minstop", "bucketed"):
        sm = outs[name]
        for f in ("served_resv", "served_prop", "last_served", "t"):
            assert bool(jnp.array_equal(getattr(base, f),
                                        getattr(sm, f))), (name, f)
        for f, x, y in zip(type(base.tracker)._fields, base.tracker,
                           sm.tracker):
            assert bool(jnp.array_equal(x, y)), (name, "tracker", f)
        for f, x, y in zip(type(base.engine)._fields, base.engine,
                           sm.engine):
            assert bool(jnp.array_equal(x, y)), (name, "engine", f)
    assert int(np.asarray(base.served_resv).sum()
               + np.asarray(base.served_prop).sum()) > 0
