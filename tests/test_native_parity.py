"""Three-way golden parity: Python oracle vs C++ native vs TPU engine.

All three backends implement the identical int64-ns tag algebra and
total order, so on any workload their decision streams must match
bit-for-bit -- this enforces the claim in ``native/src/capi.cc:5-7``.
Scenarios mirror the reference server tests
(``/root/reference/test/test_dmclock_server.cc``) plus randomized
differential fuzz; tracker parity covers both accounting policies
(``/root/reference/src/dmclock_client.h:39-154``).

Skips cleanly when no C++ toolchain is available to build
``libdmclock_c.so``.
"""

import random

import pytest

from dmclock_tpu.core import ClientInfo, Phase, ReqParams
from dmclock_tpu.core.scheduler import (AtLimit, NextReqType,
                                        PullPriorityQueue)
from dmclock_tpu.core.timebase import NS_PER_SEC
from dmclock_tpu.core.tracker import (BorrowingTracker, OrigTracker,
                                      ServiceTracker)
from dmclock_tpu.engine import TpuPullPriorityQueue

native = pytest.importorskip("dmclock_tpu.native")

if native.load_library() is None:
    pytest.skip("native dmclock library unavailable (no toolchain)",
                allow_module_level=True)

S = NS_PER_SEC


def make_trio(info_map, at_limit=AtLimit.WAIT, anticipation_ns=0,
              delayed=True, with_tpu=True):
    def info_f(c):
        return info_map[c]

    oracle = PullPriorityQueue(info_f, delayed_tag_calc=delayed,
                               at_limit=at_limit,
                               anticipation_timeout_ns=anticipation_ns,
                               run_gc_thread=False)
    nat = native.NativePullPriorityQueue(
        info_f, delayed_tag_calc=delayed, at_limit=at_limit,
        anticipation_timeout_ns=anticipation_ns)
    queues = [oracle, nat]
    if with_tpu and delayed and at_limit in (AtLimit.WAIT, AtLimit.ALLOW):
        queues.append(TpuPullPriorityQueue(
            info_f, at_limit=at_limit,
            anticipation_timeout_ns=anticipation_ns, capacity=64))
    return queues


def pull_all(queues, now_ns):
    prs = [q.pull_request(now_ns) for q in queues]
    p0 = prs[0]
    for i, p in enumerate(prs[1:], 1):
        assert p0.type == p.type, (i, p0, p)
        if p0.type is NextReqType.RETURNING:
            assert p0.client == p.client, (i, p0, p)
            assert p0.phase == p.phase
            assert p0.cost == p.cost
            assert p0.request == p.request
        elif p0.type is NextReqType.FUTURE:
            assert p0.when_ready == p.when_ready, (i, p0, p)
    return p0


def add_all(queues, request, client, rp, now, cost=1):
    rcs = {q.add_request(request, client, rp, time_ns=now, cost=cost)
           for q in queues}
    assert len(rcs) == 1, "backends disagree on add_request rc"
    return rcs.pop()


def counters_all(queues):
    triples = {(q.reserv_sched_count, q.prop_sched_count,
                q.limit_break_sched_count) for q in queues}
    assert len(triples) == 1, triples


# ----------------------------------------------------------------------
# behavioral scenarios (reference test_dmclock_server.cc re-derivations)
# ----------------------------------------------------------------------

def test_weight_ratio_three_way():
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 2, 0)}
    qs = make_trio(infos)
    t = 1 * S
    for i in range(6):
        for c in (1, 2):
            add_all(qs, ("r", c, i), c, ReqParams(), t)
    counts = {1: 0, 2: 0}
    for _ in range(6):
        pr = pull_all(qs, t + S)
        counts[pr.client] += 1
    assert counts == {1: 2, 2: 4}
    counters_all(qs)


def test_reservation_ratio_three_way():
    infos = {1: ClientInfo(2, 0, 0), 2: ClientInfo(1, 0, 0)}
    qs = make_trio(infos)
    t = 100 * S
    for i in range(6):
        for c in (1, 2):
            add_all(qs, ("r", c, i), c, ReqParams(), t)
    counts = {1: 0, 2: 0}
    for _ in range(6):
        pr = pull_all(qs, t + 100 * S)
        assert pr.phase is Phase.RESERVATION
        counts[pr.client] += 1
    assert counts == {1: 4, 2: 2}
    counters_all(qs)


def test_limit_future_none_three_way():
    infos = {1: ClientInfo(1, 1, 1)}
    qs = make_trio(infos)
    assert pull_all(qs, 1 * S).is_none()
    add_all(qs, "a", 1, ReqParams(), 10 * S)
    assert pull_all(qs, 10 * S).is_retn()
    add_all(qs, "b", 1, ReqParams(), 10 * S)
    pr = pull_all(qs, 10 * S)
    assert pr.is_future() and pr.when_ready == 11 * S


def test_allow_limit_break_three_way():
    infos = {1: ClientInfo(0, 1, 1)}
    qs = make_trio(infos, at_limit=AtLimit.ALLOW)
    t = 50 * S
    add_all(qs, "a", 1, ReqParams(), t)
    add_all(qs, "b", 1, ReqParams(), t)
    assert pull_all(qs, t).is_retn()
    assert pull_all(qs, t).is_retn()
    counters_all(qs)
    assert qs[0].limit_break_sched_count == 1


def test_reject_two_way():
    """AtLimit.REJECT (immediate tags): oracle vs native only -- the
    TPU engine is DelayedTagCalc-only by design (queue.py:11-15)."""
    infos = {1: ClientInfo(0, 1, 1)}
    qs = make_trio(infos, at_limit=AtLimit.REJECT, delayed=False,
                   with_tpu=False)
    t = 5 * S
    assert add_all(qs, "a", 1, ReqParams(), t) == 0
    # second request's limit tag is 1s out: rejected by both
    rc = add_all(qs, "b", 1, ReqParams(), t)
    assert rc != 0
    assert qs[0].request_count() == qs[1].request_count() == 1


def test_update_client_info_three_way():
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
    qs = make_trio(infos)
    t = 5 * S
    for i in range(6):
        for c in (1, 2):
            add_all(qs, ("r", c, i), c, ReqParams(), t)
    pull_all(qs, t + 1)
    infos[2].update(0, 4, 0)
    for q in qs:
        q.update_client_info(2)
    for _ in range(8):
        pull_all(qs, t + S)


def test_remove_by_client_three_way():
    infos = {1: ClientInfo(0, 1, 0), 2: ClientInfo(0, 1, 0)}
    qs = make_trio(infos)
    t = 3 * S
    for i in range(4):
        for c in (1, 2):
            add_all(qs, ("x", c, i), c, ReqParams(), t)
    got = []
    for q in qs:
        acc = []
        q.remove_by_client(1, accum=acc.append)
        got.append(acc)
    assert all(g == got[0] for g in got) and len(got[0]) == 4
    for _ in range(5):
        pull_all(qs, t + S)


# ----------------------------------------------------------------------
# randomized three-way differential fuzz
# ----------------------------------------------------------------------

@pytest.mark.parametrize("seed,at_limit,anticipation_s", [
    (31, AtLimit.WAIT, 0.0),
    (32, AtLimit.ALLOW, 0.0),
    (33, AtLimit.WAIT, 0.1),
    (34, AtLimit.ALLOW, 0.05),
])
def test_differential_three_way(seed, at_limit, anticipation_s):
    rng = random.Random(seed)
    n_clients = rng.randint(2, 10)
    infos = {}
    for c in range(n_clients):
        kind = rng.randrange(4)
        if kind == 0:
            infos[c] = ClientInfo(rng.uniform(0.5, 4), 0, 0)
        elif kind == 1:
            infos[c] = ClientInfo(0, rng.uniform(0.5, 4), 0)
        elif kind == 2:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4), rng.uniform(3, 8))
        else:
            infos[c] = ClientInfo(rng.uniform(0.5, 2),
                                  rng.uniform(0.5, 4), 0)
    qs = make_trio(infos, at_limit=at_limit,
                   anticipation_ns=int(anticipation_s * S))
    assert len(qs) == 3

    now = 1 * S
    n_retn = 0
    for step in range(150):
        now += rng.randint(0, S // 2)
        if rng.random() < 0.55:
            c = rng.randrange(n_clients)
            delta = rng.randint(1, 5)
            rho = rng.randint(1, delta)
            add_all(qs, ("req", c, step), c, ReqParams(delta, rho), now,
                    cost=rng.randint(1, 3))
        else:
            if pull_all(qs, now).is_retn():
                n_retn += 1
    for _ in range(600):
        now += 4 * S
        if pull_all(qs, now).is_retn():
            n_retn += 1
        if qs[0].request_count() == 0:
            break
    assert qs[0].request_count() == 0
    assert qs[1].request_count() == 0
    assert n_retn > 40
    counters_all(qs)


@pytest.mark.parametrize("seed", [41, 42])
def test_differential_immediate_tags_two_way(seed):
    """ImmediateTagCalc: oracle vs native (TPU is delayed-only)."""
    rng = random.Random(seed)
    infos = {c: ClientInfo(rng.uniform(0.5, 2), rng.uniform(0.5, 3),
                           rng.choice([0, 5]))
             for c in range(rng.randint(2, 8))}
    qs = make_trio(infos, delayed=False, with_tpu=False)
    now = 1 * S
    for step in range(200):
        now += rng.randint(0, S // 3)
        if rng.random() < 0.6:
            c = rng.randrange(len(infos))
            delta = rng.randint(1, 4)
            add_all(qs, (c, step), c, ReqParams(delta, rng.randint(1, delta)),
                    now, cost=rng.randint(1, 2))
        else:
            pull_all(qs, now)
    for _ in range(500):
        now += 4 * S
        pull_all(qs, now)
        if qs[0].request_count() == 0:
            break
    assert qs[0].request_count() == 0
    counters_all(qs)


# ----------------------------------------------------------------------
# tracker parity (Orig + Borrowing)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("borrowing,cls", [
    (False, OrigTracker),
    (True, BorrowingTracker),
])
def test_tracker_parity(borrowing, cls):
    rng = random.Random(7 + borrowing)
    py = ServiceTracker(tracker_cls=cls, run_gc_thread=False)
    nat = native.NativeServiceTracker(borrowing=borrowing)
    servers = ["s0", "s1", "s2"]
    outstanding = []
    for step in range(300):
        if rng.random() < 0.5 or not outstanding:
            srv = rng.choice(servers)
            a = py.get_req_params(srv)
            b = nat.get_req_params(srv)
            assert (a.delta, a.rho) == (b.delta, b.rho), \
                (step, srv, a, b)
            outstanding.append(srv)
        else:
            srv = outstanding.pop(rng.randrange(len(outstanding)))
            phase = rng.choice([Phase.RESERVATION, Phase.PRIORITY])
            cost = rng.randint(1, 3)
            py.track_resp(srv, phase, cost)
            nat.track_resp(srv, phase, cost)
    py.shutdown()
    nat.shutdown()


@pytest.mark.parametrize("seed", [81, 82])
def test_prop_heap_differential_vs_oracle(seed):
    """Native use_prop_heap (the reference USE_PROP_HEAP equivalent,
    O(1) idle-reactivation lookup) must be behaviorally invisible
    against the ORACLE across REAL idle churn: injected GC clocks
    march both queues past idle_age between bursts, do_clean marks
    sat-out clients idle, and their next add reactivates through the
    prop-heap lookup under test."""
    rng = random.Random(seed)
    infos = {c: ClientInfo(rng.choice([0, 1.0]),
                           1.0 + c % 3,
                           rng.choice([0, 4.0])) for c in range(8)}

    def info_f(c):
        return infos[c]

    fake_now = [0.0]
    oracle = PullPriorityQueue(info_f, delayed_tag_calc=True,
                               run_gc_thread=False,
                               idle_age_s=10.0, erase_age_s=1000.0,
                               check_time_s=1.0,
                               monotonic_clock=lambda: fake_now[0])
    nat = native.NativePullPriorityQueue(info_f, delayed_tag_calc=True,
                                         use_prop_heap=True,
                                         idle_age_s=10.0,
                                         erase_age_s=1000.0,
                                         check_time_s=1.0)
    nat.set_fake_clock(0.0)
    queues = [oracle, nat]
    t = 1 * S
    for burst in range(12):
        # a couple of clients sit each burst out and get marked idle
        # by the clock-marched do_clean below; their next add runs the
        # reactivation lookup against an established population
        active = [c for c in infos if (c + burst) % 4 != 0]
        for _ in range(rng.randint(3, 8)):
            c = rng.choice(active)
            t += rng.randint(0, S // 5)
            delta = rng.randint(1, 3)
            add_all(queues, ("r", burst, c, t), c,
                    ReqParams(delta, rng.randint(1, delta)), t,
                    cost=rng.randint(1, 2))
        for _ in range(rng.randint(2, 6)):
            pull_all(queues, t + rng.randint(0, S))
        t += rng.randint(1, 3) * S
        # march both GC clocks past idle_age and clean: clients that
        # sat the burst out go idle on BOTH queues
        for _ in range(12):
            fake_now[0] += 1.0
            nat.set_fake_clock(fake_now[0])
            oracle.do_clean()
            nat.do_clean()
    # drain fully; every pull must agree
    for _ in range(80):
        p = pull_all(queues, t + 100 * S)
        if p.type is not NextReqType.RETURNING:
            break
    counters_all(queues)
