#!/usr/bin/env python
"""k sweep with the device_get-digest harness + top_k cost by k."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dmclock_tpu.engine import kernels  # noqa: F401 (enables x64)
from dmclock_tpu.engine.fastpath import scan_fast_epoch
from __graft_entry__ import _preloaded_state
from profile_util import scalar_latency, state_digest as digest, \
    timed_chain

N, depth = 100_000, 64
now = jnp.int64(0)


def main():
    lat = scalar_latency()
    print(f"scalar round-trip latency: {lat*1e3:.1f} ms")

    # top_k cost vs k and dtype, as a dependent chain
    rng = np.random.default_rng(0)
    key0 = jnp.asarray(rng.integers(0, 1 << 45, N), dtype=jnp.int64)
    for dt, name in ((jnp.int64, "i64"), (jnp.int32, "i32")):
        for k in (4096, 16384):
            reps = 40

            @jax.jit
            def chain(key, k=k, dt=dt):
                kk = key.astype(dt) if dt == jnp.int32 else key
                for _ in range(reps):
                    negv, idx = lax.top_k(-kk, k)
                    kk = kk.at[idx].add(1)
                return jnp.int64(kk.sum())
            x = chain(key0)
            jax.device_get(x)  # warm
            t, _, _ = timed_chain(lambda s: s, key0, 0,
                                  chain, latency=lat)
            print(f"top_k {name} k={k:6d}: {t/reps*1e3:7.3f} ms/op")

    # epoch sweep
    for k, m in ((4096, 32), (8192, 16), (16384, 8)):
        state = _preloaded_state(N, depth, ring=depth)
        run = jax.jit(functools.partial(scan_fast_epoch, m=m, k=k,
                                        anticipation_ns=0))

        def step(st, run=run):
            return run(st, now).state
        # warm
        st = step(state)
        jax.device_get(digest(st))
        n_epochs = 6
        t, _, st2 = timed_chain(step, st, n_epochs, digest, latency=lat)
        # commit rate check (separate, untimed)
        ep = run(state, now)
        n_ok = int(jax.device_get(ep.ok).sum())
        per_epoch = t / n_epochs
        print(f"epoch k={k:6d} m={m:3d}: {per_epoch*1e3:8.2f} ms/epoch, "
              f"{per_epoch/m*1e3:7.2f} ms/batch, "
              f"{m*k/per_epoch/1e6:7.2f}M dec/s (warm ok {n_ok}/{m})")


if __name__ == "__main__":
    main()
