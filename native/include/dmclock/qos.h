// Per-client QoS parameters.
//
// Native equivalent of the reference's ClientInfo
// (/root/reference/src/dmclock_server.h:95-132) and python core/qos.py:
// (reservation, weight, limit) rates with cached integer ns-per-unit
// increments ("inverses"), 0 -> 0 meaning "axis disabled".

#pragma once

#include <ostream>

#include "time.h"

namespace dmclock {

struct ClientInfo {
  double reservation = 0.0;  // ops/sec floor
  double weight = 0.0;       // proportional share
  double limit = 0.0;        // ops/sec cap

  int64_t reservation_inv_ns = 0;
  int64_t weight_inv_ns = 0;
  int64_t limit_inv_ns = 0;

  ClientInfo() = default;
  ClientInfo(double r, double w, double l) { update(r, w, l); }

  void update(double r, double w, double l) {
    reservation = r;
    weight = w;
    limit = l;
    reservation_inv_ns = rate_to_inv_ns(r);
    weight_inv_ns = rate_to_inv_ns(w);
    limit_inv_ns = rate_to_inv_ns(l);
  }
};

inline std::ostream& operator<<(std::ostream& os, const ClientInfo& i) {
  return os << "ClientInfo(r=" << i.reservation << ", w=" << i.weight
            << ", l=" << i.limit << ")";
}

}  // namespace dmclock
