// Nanosecond profiling accumulators.
//
// Native equivalent of the reference's ProfileTimer / ProfileCombiner
// (/root/reference/support/src/profile.h:25-120) and python
// utils/profile.py: count / sum / sum-of-squares / min / max over
// timed sections, mergeable across threads.  Always compiled (the
// reference gates them behind -DPROFILE; here the sim decides at
// runtime whether to record).

#pragma once

#include <chrono>
#include <cmath>
#include <cstdint>
#include <limits>

namespace dmclock {

struct ProfileBase {
  uint64_t count = 0;
  int64_t sum_ns = 0;
  double sum_sq_ns = 0.0;  // for std-dev (reference :43-51)
  int64_t min_ns = std::numeric_limits<int64_t>::max();
  int64_t max_ns = 0;

  void record(int64_t ns) {
    ++count;
    sum_ns += ns;
    sum_sq_ns += double(ns) * double(ns);
    if (ns < min_ns) min_ns = ns;
    if (ns > max_ns) max_ns = ns;
  }

  double mean_ns() const { return count ? double(sum_ns) / count : 0.0; }

  double stddev_ns() const {
    if (count < 2) return 0.0;
    double m = mean_ns();
    return std::sqrt(sum_sq_ns / count - m * m);
  }
};

class ProfileTimer : public ProfileBase {
 public:
  void start() { start_ = std::chrono::steady_clock::now(); }
  void stop() {
    auto ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                  std::chrono::steady_clock::now() - start_)
                  .count();
    record(ns);
  }

 private:
  std::chrono::steady_clock::time_point start_;
};

// merge per-thread/per-object timers for reporting
// (reference ProfileCombiner :100-120)
struct ProfileCombiner : ProfileBase {
  void combine(const ProfileBase& o) {
    count += o.count;
    sum_ns += o.sum_ns;
    sum_sq_ns += o.sum_sq_ns;
    if (o.count) {
      if (o.min_ns < min_ns) min_ns = o.min_ns;
      if (o.max_ns > max_ns) max_ns = o.max_ns;
    }
  }
};

}  // namespace dmclock
