// Canonical int64-nanosecond time/tag algebra (C++ side).
//
// Native equivalent of python dmclock_tpu/core/timebase.py, which is the
// framework's replacement for the reference's double-seconds Time
// (/root/reference/src/dmclock_util.h:33-53).  Every backend -- Python
// oracle, this C++ runtime, the JAX engine -- performs the SAME integer
// arithmetic, so cross-backend request ordering is bit-exact.

#pragma once

#include <cfenv>
#include <cmath>
#include <cstdint>
#include <ctime>
#include <string>

namespace dmclock {

using TimeNs = int64_t;

constexpr int64_t NS_PER_SEC = 1000000000LL;

// Tag sentinels (reference max_tag/min_tag, dmclock_server.h:60-65).
constexpr int64_t MAX_TAG = int64_t{1} << 62;
constexpr int64_t MIN_TAG = -(int64_t{1} << 62);

constexpr TimeNs TIME_ZERO = 0;
constexpr TimeNs TIME_MAX = int64_t{1} << 62;

// Idle-reactivation trigger (reference uses DBL_MAX/3,
// dmclock_server.h:957-958).
constexpr int64_t LOWEST_PROP_TAG_TRIGGER = MAX_TAG / 2;

// Saturation bounds keeping int64 overflow-free (timebase.py:36-47).
constexpr int64_t MAX_INV_NS = int64_t{1} << 40;
constexpr int64_t MAX_CHARGE_UNITS = int64_t{1} << 20;
constexpr int64_t ORGANIC_TAG_CAP = MAX_TAG - 1;

// Round-half-even, matching Python round(); the default FP environment
// rounds to nearest-even, which nearbyint honors.
inline int64_t round_half_even(double v) {
  return static_cast<int64_t>(std::nearbyint(v));
}

inline TimeNs sec_to_ns(double t) { return round_half_even(t * NS_PER_SEC); }
inline double ns_to_sec(TimeNs t) { return double(t) / NS_PER_SEC; }

// QoS rate (ops/sec) -> ns of virtual time per unit cost, 0 -> 0
// "axis disabled" sentinel (reference ClientInfo::update,
// dmclock_server.h:111-118; timebase.py rate_to_inv_ns).
inline int64_t rate_to_inv_ns(double rate) {
  if (rate == 0.0) return 0;
  int64_t v = round_half_even(double(NS_PER_SEC) / rate);
  return v < MAX_INV_NS ? v : MAX_INV_NS;
}

// Wall clock in ns (reference get_time, dmclock_util.h:39-53).
inline TimeNs get_time_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return TimeNs(ts.tv_sec) * NS_PER_SEC + ts.tv_nsec;
}

// min where TIME_ZERO means "no time" (reference min_not_0_time,
// dmclock_server.h:1192-1195).
inline TimeNs min_not_0_time(TimeNs current, TimeNs possible) {
  if (possible == TIME_ZERO) return current;
  return possible < current ? possible : current;
}

// Human-readable tag (reference format_tag/format_time,
// dmclock_server.h:234-242, dmclock_util.cc:24-29).
inline std::string format_tag(int64_t value_ns, int64_t modulo = 1000000) {
  if (value_ns >= MAX_TAG) return "max";
  if (value_ns <= MIN_TAG) return "min";
  double sec = double(value_ns) / NS_PER_SEC;
  char buf[64];
  snprintf(buf, sizeof(buf), "%0.6f", std::fmod(sec, double(modulo)));
  return buf;
}

}  // namespace dmclock
