// The dmClock server-side scheduler: native (C++) backend.
//
// Equivalent of the reference's PriorityQueueBase / PullPriorityQueue /
// PushPriorityQueue (/root/reference/src/dmclock_server.h:283-1797) and
// a line-for-line semantic twin of the Python oracle
// (dmclock_tpu/core/scheduler.py) -- same int64-ns tag algebra, same
// AtLimit/anticipation/idle-reactivation/GC behavior, and the same
// TOTAL selection order: every heap comparator ends with the client
// creation index, so heap tops equal the oracle's linear-scan minima
// and request ordering is bit-identical across the C++, Python, and
// TPU backends.
//
// Departures from the reference (deliberate):
//  - delayed-vs-immediate tag calc and the heap branching factor are
//    runtime options, not template parameters (one library serves the
//    whole configuration matrix and the benchmark K sweep);
//  - times are int64 ns everywhere (see time.h).

#pragma once

#include <algorithm>
#include <cassert>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "indirect_heap.h"
#include "qos.h"
#include "recs.h"
#include "run_every.h"
#include "tags.h"
#include "time.h"

namespace dmclock {

enum class AtLimit : uint8_t { Wait = 0, Allow = 1, Reject = 2 };

enum class NextReqType : uint8_t { returning = 0, future = 1, none = 2 };

enum class HeapId : uint8_t { reservation = 0, ready = 1 };

struct NextReq {
  NextReqType type = NextReqType::none;
  HeapId heap_id = HeapId::reservation;
  TimeNs when_ready = 0;

  static NextReq none() { return NextReq{}; }
  static NextReq returning(HeapId h) {
    return NextReq{NextReqType::returning, h, 0};
  }
  static NextReq future(TimeNs when) {
    return NextReq{NextReqType::future, HeapId::reservation, when};
  }
};

// GC defaults (reference dmclock_server.h:68-72)
constexpr double STANDARD_IDLE_AGE_S = 300.0;
constexpr double STANDARD_ERASE_AGE_S = 600.0;
constexpr double STANDARD_CHECK_TIME_S = 60.0;
constexpr double AGGRESSIVE_CHECK_TIME_S = 5.0;
constexpr size_t STANDARD_ERASE_MAX = 2000;

template <typename C, typename R>
class PriorityQueueBase {
 public:
  using ClientInfoFunc = std::function<ClientInfo(const C&)>;

  struct ClientReq {
    RequestTag tag;
    C client;
    R request;
    ClientReq(const RequestTag& t, const C& c, R&& r)
        : tag(t), client(c), request(std::move(r)) {}
  };

  struct ClientRec {
    C client;
    uint64_t order;  // creation index: the deterministic tie-break
    RequestTag prev_tag;
    std::deque<ClientReq> requests;
    int64_t prop_delta = 0;  // idle-reactivation shift (ns)
    ClientInfo info;
    bool idle = true;
    uint64_t last_tick;
    uint32_t cur_rho = 1, cur_delta = 1;

    // intrusive heap slots (one per heap this record lives in)
    size_t resv_pos = HEAP_NOT_IN;
    size_t limit_pos = HEAP_NOT_IN;
    size_t ready_pos = HEAP_NOT_IN;
    size_t prop_pos = HEAP_NOT_IN;  // optional prop heap (use_prop_heap)

    ClientRec(const C& c, const ClientInfo& i, uint64_t tick, uint64_t ord)
        : client(c), order(ord), info(i), last_tick(tick) {}

    bool has_request() const { return !requests.empty(); }
    ClientReq& next_request() { return requests.front(); }
    const ClientReq& next_request() const { return requests.front(); }

    // prev-tag maintenance (reference :399-412): pinned sentinels are
    // never folded in
    void update_req_tag(const RequestTag& tag, uint64_t tick) {
      if (tag.reservation != MAX_TAG && tag.reservation != MIN_TAG)
        prev_tag.reservation = tag.reservation;
      if (tag.limit != MAX_TAG && tag.limit != MIN_TAG)
        prev_tag.limit = tag.limit;
      if (tag.proportion != MAX_TAG && tag.proportion != MIN_TAG)
        prev_tag.proportion = tag.proportion;
      prev_tag.arrival = tag.arrival;
      last_tick = tick;
    }
  };

  // --- selection total orders (oracle _resv/_limit/_ready_key;
  // reference ClientCompare :722-757 + creation-order tie-break) -----
  struct ResvCompare {
    bool operator()(const ClientRec& a, const ClientRec& b) const {
      if (a.has_request() != b.has_request()) return a.has_request();
      if (!a.has_request()) return a.order < b.order;
      int64_t ta = a.next_request().tag.reservation;
      int64_t tb = b.next_request().tag.reservation;
      if (ta != tb) return ta < tb;
      return a.order < b.order;
    }
  };
  struct LimitCompare {  // ready sorts AFTER not-ready (ready asc)
    bool operator()(const ClientRec& a, const ClientRec& b) const {
      if (a.has_request() != b.has_request()) return a.has_request();
      if (!a.has_request()) return a.order < b.order;
      bool ra = a.next_request().tag.ready, rb = b.next_request().tag.ready;
      if (ra != rb) return rb;
      int64_t ta = a.next_request().tag.limit;
      int64_t tb = b.next_request().tag.limit;
      if (ta != tb) return ta < tb;
      return a.order < b.order;
    }
  };
  struct ReadyCompare {  // ready sorts BEFORE not-ready (ready desc)
    bool operator()(const ClientRec& a, const ClientRec& b) const {
      if (a.has_request() != b.has_request()) return a.has_request();
      if (!a.has_request()) return a.order < b.order;
      bool ra = a.next_request().tag.ready, rb = b.next_request().tag.ready;
      if (ra != rb) return ra;
      int64_t ta = a.next_request().tag.proportion + a.prop_delta;
      int64_t tb = b.next_request().tag.proportion + b.prop_delta;
      if (ta != tb) return ta < tb;
      return a.order < b.order;
    }
  };

  // Optional 4th heap order (the reference's USE_PROP_HEAP,
  // dmclock_server.h:18-25, :369-371, :775-783): lowest effective
  // proportion among NON-IDLE clients, for O(1) idle-reactivation
  // lookup instead of the O(n) client scan -- the scan is the CPU
  // scaling ceiling at 10k+ clients (BASELINE.md: 62us of the 68us
  // add_request mean).  Idle clients sort last so top() is the query
  // answer whenever it is non-idle.
  struct PropCompare {
    bool operator()(const ClientRec& a, const ClientRec& b) const {
      if (a.idle != b.idle) return b.idle;
      int64_t ta = (a.has_request() ? a.next_request().tag.proportion
                                    : a.prev_tag.proportion) +
                   a.prop_delta;
      int64_t tb = (b.has_request() ? b.next_request().tag.proportion
                                    : b.prev_tag.proportion) +
                   b.prop_delta;
      if (ta != tb) return ta < tb;
      return a.order < b.order;
    }
  };

  struct Options {
    bool delayed_tag_calc = false;
    bool dynamic_cli_info = false;
    AtLimit at_limit = AtLimit::Wait;
    TimeNs reject_threshold_ns = 0;  // >0 implies AtLimit::Reject
    TimeNs anticipation_timeout_ns = 0;
    unsigned heap_branching = 2;  // the K_WAY_HEAP analog
    bool use_prop_heap = false;   // O(1) idle-reactivation lookup
    double idle_age_s = STANDARD_IDLE_AGE_S;
    double erase_age_s = STANDARD_ERASE_AGE_S;
    double check_time_s = STANDARD_CHECK_TIME_S;
    size_t erase_max = STANDARD_ERASE_MAX;
    bool run_gc_thread = false;
  };

  PriorityQueueBase(ClientInfoFunc info_f, const Options& opt)
      : client_info_f_(std::move(info_f)),
        opt_(opt),
        resv_heap_(opt.heap_branching),
        limit_heap_(opt.heap_branching),
        ready_heap_(opt.heap_branching),
        prop_heap_(opt.heap_branching) {
    if (opt_.reject_threshold_ns > 0) opt_.at_limit = AtLimit::Reject;
    // Reject needs accurate tags at add time (reference :856-857);
    // always-on like the reference's death-tested assert
    if (opt_.at_limit == AtLimit::Reject && opt_.delayed_tag_calc) {
      fprintf(stderr,
              "dmclock: AtLimit::Reject requires immediate tag calc\n");
      abort();
    }
    assert(opt_.erase_age_s >= opt_.idle_age_s);
    assert(opt_.check_time_s < opt_.idle_age_s);
    if (opt_.run_gc_thread)
      cleaning_job_ = std::make_unique<RunEvery>(
          opt_.check_time_s, [this] { do_clean(); });
  }

  virtual ~PriorityQueueBase() { shutdown(); }

  void shutdown() {
    finishing_ = true;
    cleaning_job_.reset();
  }

  // --- inspection (reference :545-564) ------------------------------
  bool empty() {
    std::lock_guard<std::mutex> g(data_mtx_);
    return resv_heap_.empty() || !resv_heap_.top().has_request();
  }
  size_t client_count() {
    std::lock_guard<std::mutex> g(data_mtx_);
    return client_map_.size();
  }
  size_t request_count() {
    std::lock_guard<std::mutex> g(data_mtx_);
    size_t n = 0;
    for (auto& kv : client_map_) n += kv.second->requests.size();
    return n;
  }

  // --- removal / info updates (reference :567-648) ------------------
  bool remove_by_req_filter(std::function<bool(R&&)> filter_accum,
                            bool visit_backwards = false) {
    std::lock_guard<std::mutex> g(data_mtx_);
    bool any_removed = false;
    for (auto& kv : client_map_) {
      ClientRec& rec = *kv.second;
      bool removed = false;
      auto& reqs = rec.requests;
      std::vector<bool> kill(reqs.size(), false);
      if (visit_backwards) {
        for (size_t i = reqs.size(); i-- > 0;)
          if (filter_accum(std::move(reqs[i].request))) {
            kill[i] = true; removed = true;
          }
      } else {
        for (size_t i = 0; i < reqs.size(); ++i)
          if (filter_accum(std::move(reqs[i].request))) {
            kill[i] = true; removed = true;
          }
      }
      if (removed) {
        std::deque<ClientReq> keep;
        for (size_t i = 0; i < reqs.size(); ++i)
          if (!kill[i]) keep.push_back(std::move(reqs[i]));
        reqs.swap(keep);
        any_removed = true;
        adjust_all_heaps(rec);
      }
    }
    return any_removed;
  }

  void remove_by_client(const C& client, bool reverse = false,
                        std::function<void(R&&)> accum = nullptr) {
    std::lock_guard<std::mutex> g(data_mtx_);
    auto it = client_map_.find(client);
    if (it == client_map_.end()) return;
    ClientRec& rec = *it->second;
    if (accum) {
      if (reverse)
        for (auto r = rec.requests.rbegin(); r != rec.requests.rend(); ++r)
          accum(std::move(r->request));
      else
        for (auto& cr : rec.requests) accum(std::move(cr.request));
    }
    rec.requests.clear();
    adjust_all_heaps(rec);
  }

  void update_client_info(const C& client) {
    std::lock_guard<std::mutex> g(data_mtx_);
    auto it = client_map_.find(client);
    if (it != client_map_.end()) {
      it->second->info = client_info_f_(client);
      adjust_all_heaps(*it->second);
    }
  }
  void update_client_infos() {
    std::lock_guard<std::mutex> g(data_mtx_);
    for (auto& kv : client_map_) {
      kv.second->info = client_info_f_(kv.second->client);
      adjust_all_heaps(*kv.second);
    }
  }

  unsigned get_heap_branching_factor() const {
    return resv_heap_.branching_factor();
  }

  // Debug dump: the three selection orders (reference display_queues
  // :676-697 / heap display_sorted; same RESER/LIMIT/READY layout as
  // the Python oracle's display_queues so dumps diff cleanly).
  std::string display_queues() {
    std::lock_guard<std::mutex> g(data_mtx_);
    std::vector<const ClientRec*> recs;
    for (auto& kv : client_map_) recs.push_back(kv.second.get());
    std::ostringstream os;
    auto section = [&](const char* name, auto cmp) {
      std::sort(recs.begin(), recs.end(),
                [&](const ClientRec* a, const ClientRec* b) {
                  return cmp(*a, *b);
                });
      os << name << ": ";
      bool first = true;
      for (const ClientRec* r : recs) {
        if (!first) os << " | ";
        first = false;
        os << r->client << ":";
        if (r->has_request()) os << r->next_request().tag;
        else os << "noreq";
      }
      os << "\n";
    };
    section("RESER", ResvCompare());
    section("LIMIT", LimitCompare());
    section("READY", ReadyCompare());
    return os.str();
  }

  // scheduling counters (reference :810-812)
  uint64_t reserv_sched_count = 0;
  uint64_t prop_sched_count = 0;
  uint64_t limit_break_sched_count = 0;

  // --- GC (reference do_clean :1206-1255) ---------------------------
  void do_clean() {
    double now = monotonic_s_();
    std::lock_guard<std::mutex> g(data_mtx_);
    clean_mark_points_.emplace_back(now, tick_);

    uint64_t erase_point = last_erase_point_;
    while (!clean_mark_points_.empty() &&
           clean_mark_points_.front().first <= now - opt_.erase_age_s) {
      last_erase_point_ = clean_mark_points_.front().second;
      erase_point = last_erase_point_;
      clean_mark_points_.pop_front();
    }
    uint64_t idle_point = 0;
    for (auto& mp : clean_mark_points_) {
      if (mp.first <= now - opt_.idle_age_s) idle_point = mp.second;
      else break;
    }
    size_t erased_num = 0;
    if (erase_point > 0 || idle_point > 0) {
      for (auto it = client_map_.begin(); it != client_map_.end();) {
        ClientRec& rec = *it->second;
        if (erase_point && erased_num < opt_.erase_max &&
            rec.last_tick <= erase_point) {
          remove_from_heaps(rec);
          it = client_map_.erase(it);
          ++erased_num;
        } else {
          if (idle_point && rec.last_tick <= idle_point) {
            rec.idle = true;
            if (opt_.use_prop_heap) prop_heap_.adjust(rec);
          }
          ++it;
        }
      }
      if (erased_num >= opt_.erase_max) {
        if (cleaning_job_) cleaning_job_->try_update(AGGRESSIVE_CHECK_TIME_S);
      } else {
        last_erase_point_ = 0;
        if (cleaning_job_) cleaning_job_->try_update(opt_.check_time_s);
      }
    }
  }

  void set_monotonic_clock(std::function<double()> f) {
    monotonic_s_ = std::move(f);
  }

 protected:
  using Heap = IndirectHeap<ClientRec, ResvCompare, &ClientRec::resv_pos>;
  using LimitHeap =
      IndirectHeap<ClientRec, LimitCompare, &ClientRec::limit_pos>;
  using ReadyHeap =
      IndirectHeap<ClientRec, ReadyCompare, &ClientRec::ready_pos>;
  using PropHeap =
      IndirectHeap<ClientRec, PropCompare, &ClientRec::prop_pos>;

  void adjust_all_heaps(ClientRec& rec) {
    resv_heap_.adjust(rec);
    limit_heap_.adjust(rec);
    ready_heap_.adjust(rec);
    if (opt_.use_prop_heap) prop_heap_.adjust(rec);
  }
  void remove_from_heaps(ClientRec& rec) {
    resv_heap_.remove(rec);
    limit_heap_.remove(rec);
    ready_heap_.remove(rec);
    if (opt_.use_prop_heap) prop_heap_.remove(rec);
  }

  const ClientInfo& get_cli_info(ClientRec& rec) {
    if (opt_.dynamic_cli_info) rec.info = client_info_f_(rec.client);
    return rec.info;
  }

  // delayed/immediate initial tag (reference :878-907)
  RequestTag initial_tag(ClientRec& rec, const ReqParams& params,
                         TimeNs time_ns, Cost cost) {
    if (opt_.delayed_tag_calc && rec.has_request()) {
      RequestTag t;  // zero tag for a non-head request
      t.arrival = time_ns;
      t.cost = cost;
      return t;
    }
    RequestTag tag(rec.prev_tag, get_cli_info(rec), params.delta,
                   params.rho, time_ns, cost,
                   opt_.anticipation_timeout_ns);
    rec.update_req_tag(tag, tick_);
    return tag;
  }

  // reference do_add_request (:913-1018); data_mtx held
  int do_add_request(R&& request, const C& client,
                     const ReqParams& req_params, TimeNs time_ns,
                     Cost cost = 1) {
    ++tick_;
    ClientRec* rec;
    auto it = client_map_.find(client);
    if (it == client_map_.end()) {
      auto r = std::make_unique<ClientRec>(client, client_info_f_(client),
                                           tick_, next_order_++);
      rec = r.get();
      client_map_.emplace(client, std::move(r));
      resv_heap_.push(rec);
      limit_heap_.push(rec);
      ready_heap_.push(rec);
      if (opt_.use_prop_heap) prop_heap_.push(rec);
    } else {
      rec = it->second.get();
    }

    if (rec->idle) {
      // idle reactivation (reference :937-985): shift the returning
      // client's effective proportion next to the lowest active tag.
      // With the prop heap the lookup is O(1) (the reference's
      // USE_PROP_HEAP, :775-783): idle clients -- including this one
      // -- sort last, so a non-idle top IS the scan's minimum.
      bool found = false;
      int64_t lowest = 0;
      if (opt_.use_prop_heap) {
        if (!prop_heap_.empty() && !prop_heap_.top().idle) {
          ClientRec& low = prop_heap_.top();
          lowest = (low.has_request()
                        ? low.next_request().tag.proportion
                        : low.prev_tag.proportion) + low.prop_delta;
          found = true;
        }
      } else {
        for (auto& kv : client_map_) {
          ClientRec& other = *kv.second;
          if (other.idle) continue;
          int64_t p = (other.has_request()
                           ? other.next_request().tag.proportion
                           : other.prev_tag.proportion) + other.prop_delta;
          if (!found || p < lowest) { lowest = p; found = true; }
        }
      }
      if (found && lowest < LOWEST_PROP_TAG_TRIGGER)
        rec->prop_delta = lowest - time_ns;
      rec->idle = false;
      if (opt_.use_prop_heap) prop_heap_.adjust(*rec);
    }

    RequestTag tag = initial_tag(*rec, req_params, time_ns, cost);

    if (opt_.at_limit == AtLimit::Reject &&
        tag.limit > time_ns + opt_.reject_threshold_ns) {
      // the rejected add still advanced prev_tag (initial_tag ->
      // update_req_tag, the reference's pinned behavior), which is a
      // prop-heap key for clients with no queued request
      if (opt_.use_prop_heap) prop_heap_.adjust(*rec);
      return EAGAIN;  // without taking ownership (reference :989-993)
    }

    rec->requests.emplace_back(tag, client, std::move(request));
    rec->cur_rho = req_params.rho;
    rec->cur_delta = req_params.delta;
    adjust_all_heaps(*rec);
    return 0;
  }

  // reference do_next_request (:1115-1186); data_mtx held
  NextReq do_next_request(TimeNs now) {
    if (resv_heap_.empty()) return NextReq::none();

    ClientRec& reserv = resv_heap_.top();
    if (reserv.has_request() &&
        reserv.next_request().tag.reservation <= now)
      return NextReq::returning(HeapId::reservation);

    // promote newly within-limit heads (reference :1135-1144)
    for (;;) {
      ClientRec& limits = limit_heap_.top();
      if (!(limits.has_request() && !limits.next_request().tag.ready &&
            limits.next_request().tag.limit <= now))
        break;
      limits.next_request().tag.ready = true;
      ready_heap_.promote(limits);
      limit_heap_.demote(limits);
    }

    ClientRec& readys = ready_heap_.top();
    if (readys.has_request() && readys.next_request().tag.ready &&
        readys.next_request().tag.proportion < MAX_TAG)
      return NextReq::returning(HeapId::ready);

    if (opt_.at_limit == AtLimit::Allow) {
      if (readys.has_request() &&
          readys.next_request().tag.proportion < MAX_TAG) {
        ++limit_break_sched_count;
        return NextReq::returning(HeapId::ready);
      } else if (reserv.has_request() &&
                 reserv.next_request().tag.reservation < MAX_TAG) {
        ++limit_break_sched_count;
        return NextReq::returning(HeapId::reservation);
      }
    }

    TimeNs next_call = TIME_MAX;
    if (resv_heap_.top().has_request())
      next_call = min_not_0_time(
          next_call, resv_heap_.top().next_request().tag.reservation);
    if (limit_heap_.top().has_request()) {
      const auto& nxt = limit_heap_.top().next_request();
      assert(!nxt.tag.ready || nxt.tag.proportion >= MAX_TAG);
      next_call = min_not_0_time(next_call, nxt.tag.limit);
    }
    if (next_call < TIME_MAX) return NextReq::future(next_call);
    return NextReq::none();
  }

  // reference pop_process_request (:1046-1073) + update_next_tag
  // (:1021-1041); data_mtx held
  template <typename Fn>
  RequestTag pop_process_request(HeapId heap, Fn&& process) {
    ClientRec& top = (heap == HeapId::reservation)
                         ? resv_heap_.top()
                         : ready_heap_.top();
    ClientReq head = std::move(top.next_request());
    RequestTag tag = head.tag;
    top.requests.pop_front();

    if (opt_.delayed_tag_calc && top.has_request()) {
      ClientReq& nxt = top.next_request();
      nxt.tag = RequestTag(tag, get_cli_info(top), top.cur_delta,
                           top.cur_rho, nxt.tag.arrival, nxt.tag.cost,
                           opt_.anticipation_timeout_ns);
      top.update_req_tag(nxt.tag, tick_);
    }

    adjust_all_heaps(top);
    process(head.client, tag.cost, std::move(head.request));
    return tag;
  }

  // reference reduce_reservation_tags (:1077-1111); data_mtx held
  void reduce_reservation_tags(const C& client, const RequestTag& tag) {
    auto it = client_map_.find(client);
    assert(it != client_map_.end());
    ClientRec& rec = *it->second;
    int64_t offset =
        rec.info.reservation_inv_ns * int64_t(tag.cost + tag.rho);
    if (opt_.delayed_tag_calc) {
      if (!rec.requests.empty())
        rec.requests.front().tag.reservation -= offset;
    } else {
      for (auto& r : rec.requests) r.tag.reservation -= offset;
    }
    rec.prev_tag.reservation -= offset;
    resv_heap_.promote(rec);
  }

  ClientInfoFunc client_info_f_;
  Options opt_;
  std::mutex data_mtx_;
  std::map<C, std::unique_ptr<ClientRec>> client_map_;
  bool finishing_ = false;
  uint64_t tick_ = 0;
  uint64_t next_order_ = 0;

  Heap resv_heap_;
  LimitHeap limit_heap_;
  ReadyHeap ready_heap_;
  PropHeap prop_heap_;

  uint64_t last_erase_point_ = 0;
  std::deque<std::pair<double, uint64_t>> clean_mark_points_;
  std::function<double()> monotonic_s_ = [] {
    return double(get_time_ns()) / NS_PER_SEC;
  };
  std::unique_ptr<RunEvery> cleaning_job_;
};

// ---------------------------------------------------------------------
// Pull mode (reference PullPriorityQueue :1279-1501)
// ---------------------------------------------------------------------

template <typename C, typename R>
struct PullReq {
  NextReqType type = NextReqType::none;
  C client{};
  R request{};
  Phase phase = Phase::reservation;
  Cost cost = 0;
  TimeNs when_ready = 0;

  bool is_none() const { return type == NextReqType::none; }
  bool is_retn() const { return type == NextReqType::returning; }
  bool is_future() const { return type == NextReqType::future; }
};

template <typename C, typename R>
class PullPriorityQueue : public PriorityQueueBase<C, R> {
  using Base = PriorityQueueBase<C, R>;

 public:
  using Base::Base;

  int add_request(R request, const C& client,
                  const ReqParams& params = ReqParams(),
                  TimeNs time_ns = -1, Cost cost = 1) {
    if (time_ns < 0) time_ns = get_time_ns();
    std::lock_guard<std::mutex> g(this->data_mtx_);
    return this->do_add_request(std::move(request), client, params,
                                time_ns, cost);
  }

  PullReq<C, R> pull_request(TimeNs now = -1) {
    if (now < 0) now = get_time_ns();
    PullReq<C, R> result;
    std::lock_guard<std::mutex> g(this->data_mtx_);
    NextReq next = this->do_next_request(now);
    result.type = next.type;
    switch (next.type) {
      case NextReqType::none:
        return result;
      case NextReqType::future:
        result.when_ready = next.when_ready;
        return result;
      case NextReqType::returning:
        break;
    }
    if (next.heap_id == HeapId::reservation) {
      result.phase = Phase::reservation;
      this->pop_process_request(
          HeapId::reservation, [&](const C& c, Cost cost, R&& req) {
            result.client = c;
            result.cost = cost;
            result.request = std::move(req);
          });
      ++this->reserv_sched_count;
    } else {
      result.phase = Phase::priority;
      RequestTag tag = this->pop_process_request(
          HeapId::ready, [&](const C& c, Cost cost, R&& req) {
            result.client = c;
            result.cost = cost;
            result.request = std::move(req);
          });
      this->reduce_reservation_tags(result.client, tag);
      ++this->prop_sched_count;
    }
    return result;
  }
};

// ---------------------------------------------------------------------
// Push mode (reference PushPriorityQueue :1504-1797)
// ---------------------------------------------------------------------

template <typename C, typename R>
class PushPriorityQueue : public PriorityQueueBase<C, R> {
  using Base = PriorityQueueBase<C, R>;

 public:
  using CanHandleFunc = std::function<bool()>;
  using HandleFunc = std::function<void(const C&, R&&, Phase, Cost)>;

  using NowFunc = std::function<TimeNs()>;
  using SchedAtFunc = std::function<void(TimeNs)>;

  PushPriorityQueue(typename Base::ClientInfoFunc info_f,
                    CanHandleFunc can_handle_f, HandleFunc handle_f,
                    const typename Base::Options& opt)
      : Base(std::move(info_f), opt),
        can_handle_f_(std::move(can_handle_f)),
        handle_f_(std::move(handle_f)),
        now_f_(get_time_ns) {
    sched_ahead_thd_ = std::thread([this] { run_sched_ahead(); });
  }

  // Virtual-time embedding (the discrete-event sim): scheduling reads
  // now_f; sched_at_f must arrange a later call to sched_ahead_fire()
  // at the given virtual time.  No sched-ahead thread is spawned.
  PushPriorityQueue(typename Base::ClientInfoFunc info_f,
                    CanHandleFunc can_handle_f, HandleFunc handle_f,
                    NowFunc now_f, SchedAtFunc sched_at_f,
                    const typename Base::Options& opt)
      : Base(std::move(info_f), opt),
        can_handle_f_(std::move(can_handle_f)),
        handle_f_(std::move(handle_f)),
        now_f_(std::move(now_f)),
        sched_at_f_(std::move(sched_at_f)) {}

  ~PushPriorityQueue() override {
    this->finishing_ = true;
    {
      std::lock_guard<std::mutex> g(sched_ahead_mtx_);
      sched_ahead_cv_.notify_all();
    }
    if (sched_ahead_thd_.joinable()) sched_ahead_thd_.join();
  }

  int add_request(R request, const C& client,
                  const ReqParams& params = ReqParams(),
                  TimeNs time_ns = -1, Cost cost = 1) {
    if (time_ns < 0) time_ns = now_f_();
    std::lock_guard<std::mutex> g(this->data_mtx_);
    int r = this->do_add_request(std::move(request), client, params,
                                 time_ns, cost);
    if (r == 0) schedule_request();
    return r;
  }

  void request_completed() {
    std::lock_guard<std::mutex> g(this->data_mtx_);
    schedule_request();
  }

  // virtual-time embedding: the sched_at_f callback landed -- disarm
  // and re-evaluate at the (virtual) now
  void sched_ahead_fire() {
    {
      std::lock_guard<std::mutex> g(sched_ahead_mtx_);
      if (this->finishing_) return;
      sched_ahead_when_ = TIME_ZERO;
    }
    std::lock_guard<std::mutex> g(this->data_mtx_);
    schedule_request();
  }

 private:
  // reference submit_top_request/submit_request (:1674-1715);
  // data_mtx held
  void submit_request(HeapId heap) {
    C client{};
    if (heap == HeapId::reservation) {
      this->pop_process_request(heap,
                                [&](const C& c, Cost cost, R&& req) {
                                  client = c;
                                  handle_f_(c, std::move(req),
                                            Phase::reservation, cost);
                                });
      ++this->reserv_sched_count;
    } else {
      RequestTag tag = this->pop_process_request(
          heap, [&](const C& c, Cost cost, R&& req) {
            client = c;
            handle_f_(c, std::move(req), Phase::priority, cost);
          });
      this->reduce_reservation_tags(client, tag);
      ++this->prop_sched_count;
    }
  }

  // reference schedule_request (:1741-1755); data_mtx held
  void schedule_request() {
    if (!can_handle_f_()) return;
    TimeNs now = now_f_();
    NextReq next = this->do_next_request(now);
    switch (next.type) {
      case NextReqType::returning:
        submit_request(next.heap_id);
        break;
      case NextReqType::future:
        sched_at(next.when_ready);
        break;
      case NextReqType::none:
        break;
    }
  }

  // reference sched_at (:1789-1796); with a virtual sched_at_f the
  // armed-deadline dedup still applies
  void sched_at(TimeNs when) {
    std::lock_guard<std::mutex> g(sched_ahead_mtx_);
    if (this->finishing_) return;
    if (sched_ahead_when_ == TIME_ZERO || when < sched_ahead_when_) {
      sched_ahead_when_ = when;
      if (sched_at_f_) sched_at_f_(when);
      else sched_ahead_cv_.notify_all();
    }
  }

  // reference run_sched_ahead (:1760-1786)
  void run_sched_ahead() {
    std::unique_lock<std::mutex> lk(sched_ahead_mtx_);
    while (!this->finishing_) {
      if (sched_ahead_when_ == TIME_ZERO) {
        sched_ahead_cv_.wait(lk);
        continue;
      }
      TimeNs now = get_time_ns();
      if (sched_ahead_when_ > now) {
        sched_ahead_cv_.wait_for(
            lk, std::chrono::nanoseconds(sched_ahead_when_ - now));
        continue;
      }
      sched_ahead_when_ = TIME_ZERO;
      if (this->finishing_) return;
      lk.unlock();
      {
        std::lock_guard<std::mutex> g(this->data_mtx_);
        schedule_request();
      }
      lk.lock();
    }
  }

  CanHandleFunc can_handle_f_;
  HandleFunc handle_f_;
  NowFunc now_f_;
  SchedAtFunc sched_at_f_;
  std::mutex sched_ahead_mtx_;
  std::condition_variable sched_ahead_cv_;
  TimeNs sched_ahead_when_ = TIME_ZERO;
  std::thread sched_ahead_thd_;
};

}  // namespace dmclock
