// Client-side distributed service tracking.
//
// Native equivalent of the reference's ServiceTracker with pluggable
// OrigTracker / BorrowingTracker accounting
// (/root/reference/src/dmclock_client.h:39-287) and python
// core/tracker.py: a client keeps global completion counters and one
// per-server tracker; each request carries the counter movement since
// the previous request to that server minus the client's own
// contribution there.

#pragma once

#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <utility>

#include "recs.h"
#include "run_every.h"
#include "time.h"

namespace dmclock {

struct GlobalCounters {
  // start at 1: 0 is reserved by the cleaning logic
  // (reference dmclock_client.h:191-198)
  Counter delta = 1;
  Counter rho = 1;
};

// best-effort original accounting (reference dmclock_client.h:39-84)
class OrigTracker {
 public:
  OrigTracker(Counter global_delta, Counter global_rho)
      : delta_prev_req_(global_delta), rho_prev_req_(global_rho) {}

  ReqParams prepare_req(GlobalCounters& c) {
    Counter delta_out = c.delta - delta_prev_req_ - my_delta_;
    Counter rho_out = c.rho - rho_prev_req_ - my_rho_;
    delta_prev_req_ = c.delta;
    rho_prev_req_ = c.rho;
    my_delta_ = 0;
    my_rho_ = 0;
    return ReqParams(uint32_t(delta_out), uint32_t(rho_out));
  }

  void resp_update(Phase phase, GlobalCounters& c, Cost cost) {
    c.delta += cost;
    my_delta_ += cost;
    if (phase == Phase::reservation) {
      c.rho += cost;
      my_rho_ += cost;
    }
  }

  Counter get_last_delta() const { return delta_prev_req_; }

 private:
  Counter delta_prev_req_;
  Counter rho_prev_req_;
  Counter my_delta_ = 0;
  Counter my_rho_ = 0;
};

// always-positive accounting by borrowing future replies
// (reference dmclock_client.h:90-154)
class BorrowingTracker {
 public:
  BorrowingTracker(Counter global_delta, Counter global_rho)
      : delta_prev_req_(global_delta), rho_prev_req_(global_rho) {}

  static std::pair<Counter, Counter> calc_with_borrow(Counter global,
                                                      Counter previous,
                                                      Counter borrow) {
    Counter result = global - previous;
    if (result == 0) return {1, borrow + 1};
    if (result > borrow) return {result - borrow, 0};
    return {1, borrow - result + 1};
  }

  ReqParams prepare_req(GlobalCounters& c) {
    auto [d_out, d_borrow] =
        calc_with_borrow(c.delta, delta_prev_req_, delta_borrow_);
    auto [r_out, r_borrow] =
        calc_with_borrow(c.rho, rho_prev_req_, rho_borrow_);
    delta_borrow_ = d_borrow;
    rho_borrow_ = r_borrow;
    delta_prev_req_ = c.delta;
    rho_prev_req_ = c.rho;
    return ReqParams(uint32_t(d_out), uint32_t(r_out));
  }

  void resp_update(Phase phase, GlobalCounters& c, Cost cost) {
    c.delta += cost;
    if (phase == Phase::reservation) c.rho += cost;
  }

  Counter get_last_delta() const { return delta_prev_req_; }

 private:
  Counter delta_prev_req_;
  Counter rho_prev_req_;
  Counter delta_borrow_ = 0;
  Counter rho_borrow_ = 0;
};

// per-client distributed state across servers
// (reference ServiceTracker, dmclock_client.h:157-287)
template <typename S, typename T = OrigTracker>
class ServiceTracker {
 public:
  explicit ServiceTracker(double clean_every_s = 300.0,
                          double clean_age_s = 600.0,
                          bool run_gc_thread = false)
      : clean_age_s_(clean_age_s) {
    if (run_gc_thread)
      cleaning_job_ = std::make_unique<RunEvery>(
          clean_every_s, [this] { do_clean(); });
  }

  ~ServiceTracker() { cleaning_job_.reset(); }

  // incorporate a response; self-heals for unknown/GC'd servers
  // (reference track_resp :221-236)
  void track_resp(const S& server, Phase phase, Cost cost = 1) {
    std::lock_guard<std::mutex> g(mtx_);
    auto it = server_map_.find(server);
    if (it == server_map_.end())
      it = server_map_.emplace(server, T(counters_.delta, counters_.rho))
               .first;
    it->second.resp_update(phase, counters_, cost);
  }

  // ReqParams for the next request to `server`
  // (reference get_req_params :241-251)
  ReqParams get_req_params(const S& server) {
    std::lock_guard<std::mutex> g(mtx_);
    auto it = server_map_.find(server);
    if (it == server_map_.end()) {
      server_map_.emplace(server, T(counters_.delta, counters_.rho));
      return ReqParams(1, 1);
    }
    return it->second.prepare_req(counters_);
  }

  // GC server records unused for clean_age (reference do_clean :263-286)
  void do_clean() {
    double now = monotonic_s_();
    std::lock_guard<std::mutex> g(mtx_);
    clean_mark_points_.emplace_back(now, counters_.delta);
    Counter earliest = 0;
    while (!clean_mark_points_.empty() &&
           clean_mark_points_.front().first <= now - clean_age_s_) {
      earliest = clean_mark_points_.front().second;
      clean_mark_points_.pop_front();
    }
    if (earliest > 0) {
      for (auto it = server_map_.begin(); it != server_map_.end();) {
        if (it->second.get_last_delta() <= earliest)
          it = server_map_.erase(it);
        else
          ++it;
      }
    }
  }

  size_t server_count() {
    std::lock_guard<std::mutex> g(mtx_);
    return server_map_.size();
  }

  void set_monotonic_clock(std::function<double()> f) {
    monotonic_s_ = std::move(f);
  }

 private:
  GlobalCounters counters_;
  std::map<S, T> server_map_;
  std::mutex mtx_;
  double clean_age_s_;
  std::deque<std::pair<double, Counter>> clean_mark_points_;
  std::function<double()> monotonic_s_ = [] {
    return double(get_time_ns()) / NS_PER_SEC;
  };
  std::unique_ptr<RunEvery> cleaning_job_;
};

}  // namespace dmclock
