// Indirect intrusive k-way min-heap.
//
// Native equivalent of the reference's load-bearing data structure
// (/root/reference/support/src/indirect_intrusive_heap.h:47-565),
// redesigned: elements are held by pointer ("indirect") and every
// element stores its own position in a caller-chosen member
// ("intrusive"), giving O(1) element->slot lookup so schedulers can
// promote/demote/adjust/remove an element in place without searching.
// One element can sit in several heaps at once by dedicating one index
// member per heap (the dmclock scheduler keeps each client in three).
//
// Differences from the reference by design: K is a runtime constructor
// argument rather than a template parameter (one binary serves the
// whole K sweep in the benchmark pipeline), and there is a single
// sift_down for all K (the compiler unrolls the K==2 case well enough;
// see native/benchmark).

#pragma once

#include <algorithm>
#include <cassert>
#include <cstddef>
#include <cstdint>
#include <functional>
#include <ostream>
#include <vector>

namespace dmclock {

constexpr size_t HEAP_NOT_IN = SIZE_MAX;

// T must be a class with a `size_t T::*Index` member reserved for this
// heap; Compare is a strict-weak "less" over T.
template <typename T, typename Compare, size_t T::*Index>
class IndirectHeap {
 public:
  explicit IndirectHeap(unsigned branching = 2, Compare cmp = Compare())
      : k_(branching < 2 ? 2 : branching), cmp_(cmp) {}

  bool empty() const { return data_.empty(); }
  size_t size() const { return data_.size(); }

  T& top() { assert(!data_.empty()); return *data_[0]; }
  const T& top() const { assert(!data_.empty()); return *data_[0]; }

  T& at(size_t i) { return *data_[i]; }

  bool contains(const T& elem) const { return elem.*Index != HEAP_NOT_IN; }

  void push(T* elem) {
    size_t i = data_.size();
    data_.push_back(elem);
    elem->*Index = i;
    sift_up(i);
  }

  void pop() {
    assert(!data_.empty());
    data_[0]->*Index = HEAP_NOT_IN;
    if (data_.size() > 1) {
      data_[0] = data_.back();
      data_[0]->*Index = 0;
      data_.pop_back();
      sift_down(0);
    } else {
      data_.pop_back();
    }
  }

  // re-establish heap order for an element whose key changed; sifts in
  // whichever direction is needed (reference adjust, :365-367)
  void adjust(T& elem) {
    size_t i = elem.*Index;
    assert(i != HEAP_NOT_IN && i < data_.size());
    sift_up(i);
    if (data_[i] == &elem) sift_down(i);
  }

  // key got smaller (reference promote, :357-359)
  void promote(T& elem) { sift_up(elem.*Index); }

  // key got larger (reference demote, :361-363)
  void demote(T& elem) { sift_down(elem.*Index); }

  void remove(T& elem) {
    size_t i = elem.*Index;
    assert(i != HEAP_NOT_IN && i < data_.size());
    data_[i]->*Index = HEAP_NOT_IN;
    if (i == data_.size() - 1) {
      data_.pop_back();
      return;
    }
    T* filler = data_.back();
    data_[i] = filler;
    data_[i]->*Index = i;
    data_.pop_back();
    // the filler can need movement either way (reference notes the
    // same subtlety at indirect_intrusive_heap.h:437-441): sift down
    // only if sift_up left it in place
    sift_up(i);
    if (i < data_.size() && data_[i] == filler) sift_down(i);
  }

  // iteration over raw storage (heap order, not sorted)
  typename std::vector<T*>::iterator begin() { return data_.begin(); }
  typename std::vector<T*>::iterator end() { return data_.end(); }
  typename std::vector<T*>::const_iterator begin() const {
    return data_.begin();
  }
  typename std::vector<T*>::const_iterator end() const {
    return data_.end();
  }

  // search surface (reference indirect_intrusive_heap.h:68-203
  // iterators/find/rfind): O(1) via the intrusive index when the
  // element is known, predicate scans otherwise.  `find(elem)`
  // returns end() for elements not in this heap.
  typename std::vector<T*>::iterator find(const T& elem) {
    size_t i = elem.*Index;
    if (i == HEAP_NOT_IN || i >= data_.size() || data_[i] != &elem)
      return data_.end();
    return data_.begin() + i;
  }

  typename std::vector<T*>::const_iterator find(const T& elem) const {
    size_t i = elem.*Index;
    if (i == HEAP_NOT_IN || i >= data_.size() || data_[i] != &elem)
      return data_.end();
    return data_.begin() + i;
  }

  template <typename Pred>
  typename std::vector<T*>::iterator find_if(Pred&& pred) {
    return std::find_if(data_.begin(), data_.end(),
                        [&](T* e) { return pred(*e); });
  }

  template <typename Pred>
  typename std::vector<T*>::const_iterator find_if(Pred&& pred) const {
    return std::find_if(data_.begin(), data_.end(),
                        [&](T* e) { return pred(*e); });
  }

  // reverse-order predicate search (the reference's rfind: useful
  // when the target is likely near the heap's bottom, e.g. a
  // just-pushed element)
  template <typename Pred>
  typename std::vector<T*>::iterator rfind_if(Pred&& pred) {
    auto rit = std::find_if(data_.rbegin(), data_.rend(),
                            [&](T* e) { return pred(*e); });
    return rit == data_.rend() ? data_.end() : std::prev(rit.base());
  }

  template <typename Fn>
  void display_sorted(std::ostream& os, Fn&& fmt) const {
    std::vector<T*> copy = data_;
    std::sort(copy.begin(), copy.end(),
              [this](T* a, T* b) { return cmp_(*a, *b); });
    for (T* e : copy) fmt(os, *e);
  }

  unsigned branching_factor() const { return k_; }

 private:
  void sift_up(size_t i) {
    while (i > 0) {
      size_t parent = (i - 1) / k_;
      if (!cmp_(*data_[i], *data_[parent])) break;
      swap_at(i, parent);
      i = parent;
    }
  }

  void sift_down(size_t i) {
    const size_t n = data_.size();
    for (;;) {
      size_t first = i * k_ + 1;
      if (first >= n) break;
      size_t last = first + k_;
      if (last > n) last = n;
      size_t best = first;
      for (size_t c = first + 1; c < last; ++c)
        if (cmp_(*data_[c], *data_[best])) best = c;
      if (!cmp_(*data_[best], *data_[i])) break;
      swap_at(i, best);
      i = best;
    }
  }

  void swap_at(size_t a, size_t b) {
    std::swap(data_[a], data_[b]);
    data_[a]->*Index = a;
    data_[b]->*Index = b;
  }

  unsigned k_;
  Compare cmp_;
  std::vector<T*> data_;
};

}  // namespace dmclock
