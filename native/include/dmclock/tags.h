// The mClock/dmClock request-tag algebra, int64-ns fixed point.
//
// Native equivalent of the reference's RequestTag + tag_calc
// (/root/reference/src/dmclock_server.h:135-274) and python
// core/tags.py:
//   reservation = max(t, prev_r + r_inv * (rho   + cost))
//   proportion  = max(t, prev_p + w_inv * (delta + cost))
//   limit       = max(t, prev_l + l_inv * (delta + cost))
// with zero inverses pinning to MAX_TAG/MIN_TAG and anticipation
// backdating arrivals inside the window (:159-161).

#pragma once

#include <algorithm>
#include <cassert>
#include <cstdio>
#include <cstdlib>

#include "qos.h"
#include "recs.h"
#include "time.h"

namespace dmclock {

inline int64_t tag_calc(TimeNs time_ns, int64_t prev_ns, int64_t inv_ns,
                        int64_t dist_val, bool extreme_is_high,
                        int64_t cost) {
  if (inv_ns == 0) return extreme_is_high ? MAX_TAG : MIN_TAG;
  int64_t units = std::min(dist_val + cost, MAX_CHARGE_UNITS);
  int64_t organic = std::max(time_ns, prev_ns + inv_ns * units);
  return std::min(organic, ORGANIC_TAG_CAP);
}

struct RequestTag {
  int64_t reservation = 0;
  int64_t proportion = 0;
  int64_t limit = 0;
  TimeNs arrival = 0;
  uint32_t delta = 0;
  uint32_t rho = 0;
  Cost cost = 1;
  bool ready = false;  // limit has passed; weight-phase eligible

  RequestTag() = default;

  // The tag recurrence (reference dmclock_server.h:145-183).
  RequestTag(const RequestTag& prev, const ClientInfo& info, uint32_t d,
             uint32_t r, TimeNs time_ns, Cost c,
             TimeNs anticipation_timeout_ns = 0)
      : arrival(time_ns), delta(d), rho(r), cost(c), ready(false) {
    assert(c > 0);
    TimeNs max_time = time_ns;
    if (time_ns - anticipation_timeout_ns < prev.arrival)
      max_time -= anticipation_timeout_ns;
    reservation = tag_calc(max_time, prev.reservation,
                           info.reservation_inv_ns, r, true, c);
    proportion = tag_calc(max_time, prev.proportion, info.weight_inv_ns,
                          d, true, c);
    limit = tag_calc(max_time, prev.limit, info.limit_inv_ns, d, false, c);
    // a client with neither reservation nor weight can never be
    // scheduled; always-on (the reference death-tests this contract,
    // test_dmclock_server.cc:51-97, and Release strips assert)
    if (!(reservation < MAX_TAG || proportion < MAX_TAG)) {
      fprintf(stderr,
              "dmclock: client with zero reservation and zero weight\n");
      abort();
    }
  }
};

inline std::ostream& operator<<(std::ostream& os, const RequestTag& t) {
  return os << "{ RequestTag:: ready:" << (t.ready ? "true" : "false")
            << " r:" << format_tag(t.reservation)
            << " p:" << format_tag(t.proportion)
            << " l:" << format_tag(t.limit) << " }";
}

}  // namespace dmclock
