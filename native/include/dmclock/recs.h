// Shared wire-level record types of the dmClock protocol.
//
// Native equivalent of the reference's dmclock_recs.h
// (/root/reference/src/dmclock_recs.h:25-72) and python core/recs.py:
// Counter/Cost scalars, the phase marker a server returns with each
// response, and ReqParams{delta, rho} -- the entire piggybacked payload
// of the distributed protocol.

#pragma once

#include <cassert>
#include <cstdint>
#include <ostream>

namespace dmclock {

using Counter = uint64_t;
using Cost = uint32_t;

enum class Phase : uint8_t { reservation = 0, priority = 1 };

inline std::ostream& operator<<(std::ostream& os, Phase p) {
  return os << (p == Phase::reservation ? "reservation" : "priority");
}

struct ReqParams {
  // delta: all completions this client saw since its previous request
  // to the receiving server; rho: the reservation-phase subset.
  // Invariant rho <= delta (dmclock_recs.h:51).
  uint32_t delta = 0;
  uint32_t rho = 0;

  ReqParams() = default;
  ReqParams(uint32_t d, uint32_t r) : delta(d), rho(r) { assert(rho <= delta); }
};

inline std::ostream& operator<<(std::ostream& os, const ReqParams& rp) {
  return os << "ReqParams{ delta:" << rp.delta << ", rho:" << rp.rho << " }";
}

}  // namespace dmclock
