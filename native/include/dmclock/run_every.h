// Periodic background job thread.
//
// Native equivalent of the reference's RunEvery
// (/root/reference/support/src/run_every.h:32-80, run_every.cc:61-94)
// and python utils/periodic.py: runs a callback every period on its own
// thread; the period can be changed on the fly (try_update); the
// destructor stops and joins.

#pragma once

#include <chrono>
#include <condition_variable>
#include <functional>
#include <mutex>
#include <thread>

namespace dmclock {

class RunEvery {
 public:
  RunEvery(double period_s, std::function<void()> body)
      : period_(std::chrono::duration<double>(period_s)),
        body_(std::move(body)),
        thread_([this] { run(); }) {}

  ~RunEvery() { join(); }

  RunEvery(const RunEvery&) = delete;
  RunEvery& operator=(const RunEvery&) = delete;

  void join() {
    {
      std::lock_guard<std::mutex> g(mtx_);
      if (finishing_) return;
      finishing_ = true;
      cv_.notify_all();
    }
    if (thread_.joinable()) thread_.join();
  }

  // update the period; takes effect from the next wait
  // (reference try_update, run_every.cc:77-81)
  void try_update(double period_s) {
    std::lock_guard<std::mutex> g(mtx_);
    period_ = std::chrono::duration<double>(period_s);
    cv_.notify_all();
  }

 private:
  void run() {
    std::unique_lock<std::mutex> lk(mtx_);
    while (!finishing_) {
      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration_cast<
                          std::chrono::steady_clock::duration>(period_);
      while (!finishing_ && std::chrono::steady_clock::now() < deadline)
        cv_.wait_until(lk, deadline);
      if (finishing_) break;
      lk.unlock();
      body_();
      lk.lock();
    }
  }

  std::chrono::duration<double> period_;
  std::function<void()> body_;
  std::mutex mtx_;
  std::condition_variable cv_;
  bool finishing_ = false;
  std::thread thread_;
};

}  // namespace dmclock
