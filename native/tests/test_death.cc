// Death tests: invalid configurations must abort, fork-based (the
// reference gtest suite death-tests the same contracts with
// EXPECT_DEATH + a PrCtl coredump guard,
// /root/reference/test/test_dmclock_server.cc:51-97 + test/dmcPrCtl.h;
// gtest is unavailable here, so each case runs in a forked child and
// the parent asserts on SIGABRT).  Also a heap fuzz against a sorted
// model (reference test_indirect_intrusive_heap.cc:266-465 territory,
// extended with an oracle).

#include <sys/prctl.h>
#include <sys/resource.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <memory>
#include <random>
#include <vector>

#include "dmclock/indirect_heap.h"
#include "dmclock/scheduler.h"
#include "microtest.h"

using namespace dmclock;

using Q = PullPriorityQueue<uint64_t, uint64_t>;
constexpr int64_t S = NS_PER_SEC;

// Runs fn() in a forked child with coredumps disabled; returns true
// iff the child died with SIGABRT.
template <typename Fn>
static bool dies_with_abort(Fn&& fn) {
  pid_t pid = fork();
  if (pid == 0) {
    // no coredump, no stderr spam from the expected abort message
    prctl(PR_SET_DUMPABLE, 0);
    struct rlimit rl {0, 0};
    setrlimit(RLIMIT_CORE, &rl);
    freopen("/dev/null", "w", stderr);
    fn();
    _exit(0);  // survived: NOT a death
  }
  int status = 0;
  waitpid(pid, &status, 0);
  return WIFSIGNALED(status) && WTERMSIG(status) == SIGABRT;
}

MT_TEST(zero_reservation_and_weight_aborts) {
  // reference bad_tag_deathtest client1: r=0 w=0 l=0
  MT_CHECK(dies_with_abort([] {
    Q q([](const uint64_t&) { return ClientInfo(0.0, 0.0, 0.0); },
        Q::Options{});
    q.add_request(1, 17, ReqParams(1, 1), 1 * S, 1);
  }));
}

MT_TEST(zero_rw_with_limit_still_aborts) {
  // reference bad_tag_deathtest client2: r=0 w=0 l=1 -- a limit alone
  // cannot make a client schedulable
  MT_CHECK(dies_with_abort([] {
    Q q([](const uint64_t&) { return ClientInfo(0.0, 0.0, 1.0); },
        Q::Options{});
    q.add_request(1, 18, ReqParams(1, 1), 1 * S, 1);
  }));
}

MT_TEST(reject_with_delayed_tags_aborts) {
  // reference: Queue(client_info_f, AtLimit::Reject) with delayed
  // calc must die (reference :856-857 static assert analog)
  MT_CHECK(dies_with_abort([] {
    Q::Options o;
    o.delayed_tag_calc = true;
    o.at_limit = AtLimit::Reject;
    Q q([](const uint64_t&) { return ClientInfo(1.0, 1.0, 0.0); }, o);
  }));
}

MT_TEST(valid_configs_do_not_abort) {
  // negative control: the harness must distinguish death from life
  MT_CHECK(!dies_with_abort([] {
    Q q([](const uint64_t&) { return ClientInfo(1.0, 1.0, 0.0); },
        Q::Options{});
    q.add_request(1, 17, ReqParams(1, 1), 1 * S, 1);
    (void)q.pull_request(2 * S);
  }));
  MT_CHECK(!dies_with_abort([] {
    // Reject with IMMEDIATE tags is the supported combination
    Q::Options o;
    o.delayed_tag_calc = false;
    o.at_limit = AtLimit::Reject;
    o.reject_threshold_ns = S;
    Q q([](const uint64_t&) { return ClientInfo(1.0, 1.0, 2.0); }, o);
  }));
}

// ---------------------------------------------------------------------
// heap fuzz vs a sorted model: every operation interleaving must keep
// top() == model minimum, and the final drain must come out sorted
// ---------------------------------------------------------------------

struct FElem {
  int key;
  size_t pos = dmclock::HEAP_NOT_IN;
  explicit FElem(int k) : key(k) {}
};
struct FCmp {
  bool operator()(const FElem& a, const FElem& b) const {
    return a.key < b.key;
  }
};
using FHeap = IndirectHeap<FElem, FCmp, &FElem::pos>;

MT_TEST(heap_fuzz_vs_sorted_model) {
  std::mt19937 rng(1234);
  for (unsigned k : {2u, 3u, 5u, 8u}) {
    FHeap h(k);
    std::vector<std::unique_ptr<FElem>> owner;
    std::vector<FElem*> live;  // model: membership list
    int unique = 0;
    for (int step = 0; step < 4000; ++step) {
      int op = int(rng() % 100);
      if (op < 40 || live.empty()) {          // push
        owner.push_back(std::make_unique<FElem>(
            int((rng() % 100000) << 8 | (unique++ & 0xFF))));
        live.push_back(owner.back().get());
        h.push(owner.back().get());
      } else if (op < 60) {                   // pop-min
        FElem* top = &h.top();
        auto it = std::min_element(
            live.begin(), live.end(),
            [](FElem* a, FElem* b) { return a->key < b->key; });
        MT_CHECK(top == *it);                 // exact element identity
        h.pop();
        live.erase(std::find(live.begin(), live.end(), top));
      } else if (op < 80) {                   // adjust (rekey in place)
        FElem* e = live[rng() % live.size()];
        e->key = int((rng() % 100000) << 8 | (unique++ & 0xFF));
        h.adjust(*e);
      } else {                                // remove arbitrary
        FElem* e = live[rng() % live.size()];
        h.remove(*e);
        live.erase(std::find(live.begin(), live.end(), e));
      }
      if (!live.empty()) {
        auto it = std::min_element(
            live.begin(), live.end(),
            [](FElem* a, FElem* b) { return a->key < b->key; });
        MT_CHECK(h.top().key == (*it)->key);
      } else {
        MT_CHECK(h.empty());
      }
    }
    // drain: must come out in sorted order and match the model set
    std::vector<int> drained, expect;
    for (FElem* e : live) expect.push_back(e->key);
    std::sort(expect.begin(), expect.end());
    while (!h.empty()) {
      drained.push_back(h.top().key);
      h.pop();
    }
    MT_CHECK(drained == expect);
  }
}

int main() { return microtest::run_all(); }
