// Native ServiceTracker tests, mirroring the reference client suite
// (/root/reference/test/test_dmclock_client.cc): exact delta/rho
// sequences for Orig and Borrowing accounting across interleaved
// multi-server responses, plus GC of dead server records.

#include "dmclock/tracker.h"
#include "microtest.h"

using namespace dmclock;

MT_TEST(orig_tracker_sequences) {
  // mirrors test_dmclock_client.cc:231-304's counting style
  ServiceTracker<uint64_t, OrigTracker> st;
  auto rp = st.get_req_params(1);  // first contact
  MT_CHECK_EQ(rp.delta, 1u);
  MT_CHECK_EQ(rp.rho, 1u);
  // responses: 2 from server1 (one reservation), 1 from server2
  st.track_resp(1, Phase::reservation, 1);
  st.track_resp(1, Phase::priority, 1);
  auto rp2 = st.get_req_params(2);  // first contact with 2
  MT_CHECK_EQ(rp2.delta, 1u);
  st.track_resp(2, Phase::priority, 1);
  // server1 sees everything since last request there MINUS its own
  // deliveries (2 own + 1 from server2 -> delta = 1)
  auto rp3 = st.get_req_params(1);
  MT_CHECK_EQ(rp3.delta, 1u);
  MT_CHECK_EQ(rp3.rho, 0u);
  // nothing happened since: zero movement
  auto rp4 = st.get_req_params(1);
  MT_CHECK_EQ(rp4.delta, 0u);
  MT_CHECK_EQ(rp4.rho, 0u);
}

MT_TEST(borrowing_tracker_floors_at_one) {
  // BorrowingTracker guarantees >=1 by borrowing future replies
  // (reference calc_with_borrow :110-129)
  ServiceTracker<uint64_t, BorrowingTracker> st;
  auto rp = st.get_req_params(1);
  MT_CHECK_EQ(rp.delta, 1u);
  // no traffic at all; still reports 1 and accrues borrow
  auto rp2 = st.get_req_params(1);
  MT_CHECK_EQ(rp2.delta, 1u);
  MT_CHECK_EQ(rp2.rho, 1u);
  // two completions arrive; one is owed to the borrow
  st.track_resp(1, Phase::reservation, 1);
  st.track_resp(1, Phase::priority, 1);
  auto rp3 = st.get_req_params(1);
  MT_CHECK_EQ(rp3.delta, 1u);  // 2 seen - 1 borrowed
}

MT_TEST(calc_with_borrow_cases) {
  // (global-previous, borrow) -> (out, new_borrow)
  auto r1 = BorrowingTracker::calc_with_borrow(10, 10, 0);
  MT_CHECK_EQ(r1.first, Counter{1});
  MT_CHECK_EQ(r1.second, Counter{1});
  auto r2 = BorrowingTracker::calc_with_borrow(15, 10, 2);
  MT_CHECK_EQ(r2.first, Counter{3});
  MT_CHECK_EQ(r2.second, Counter{0});
  auto r3 = BorrowingTracker::calc_with_borrow(12, 10, 5);
  MT_CHECK_EQ(r3.first, Counter{1});
  MT_CHECK_EQ(r3.second, Counter{4});
}

MT_TEST(borrowing_interleaved_two_servers) {
  // hand-derived delta/rho stream across two servers with mixed costs
  // (the reference pins the same algebra in
  // test_dmclock_client.cc:108-225); globals start at 1/1
  ServiceTracker<uint64_t, BorrowingTracker> st;
  auto r1 = st.get_req_params(1);              // first contact s1
  MT_CHECK_EQ(r1.delta, 1u); MT_CHECK_EQ(r1.rho, 1u);
  st.track_resp(1, Phase::reservation, 2);     // delta 3, rho 3
  auto r2 = st.get_req_params(2);              // first contact s2
  MT_CHECK_EQ(r2.delta, 1u); MT_CHECK_EQ(r2.rho, 1u);
  st.track_resp(2, Phase::priority, 1);        // delta 4
  auto r3 = st.get_req_params(1);              // (4-1, 3-1) no borrow
  MT_CHECK_EQ(r3.delta, 3u); MT_CHECK_EQ(r3.rho, 2u);
  auto r4 = st.get_req_params(1);              // no movement: borrow
  MT_CHECK_EQ(r4.delta, 1u); MT_CHECK_EQ(r4.rho, 1u);
  st.track_resp(1, Phase::priority, 1);        // delta 5
  auto r5 = st.get_req_params(1);              // +1 vs borrow 1 -> 1
  MT_CHECK_EQ(r5.delta, 1u); MT_CHECK_EQ(r5.rho, 1u);
  st.track_resp(1, Phase::reservation, 3);     // delta 8, rho 6
  auto r6 = st.get_req_params(1);              // +3 minus borrow 1 / +3 minus borrow 2
  MT_CHECK_EQ(r6.delta, 2u); MT_CHECK_EQ(r6.rho, 1u);
  auto r7 = st.get_req_params(2);              // s2 saw it all: (5, 3)
  MT_CHECK_EQ(r7.delta, 5u); MT_CHECK_EQ(r7.rho, 3u);
}

MT_TEST(server_record_gc) {
  // mirrors reference server_erase (:42-105): a server unused past
  // clean_age is forgotten; tracker self-heals on its return
  ServiceTracker<uint64_t, OrigTracker> st(/*clean_every_s=*/1.0,
                                           /*clean_age_s=*/10.0,
                                           /*run_gc_thread=*/false);
  double fake_now = 0.0;
  st.set_monotonic_clock([&] { return fake_now; });
  (void)st.get_req_params(1);
  (void)st.get_req_params(2);
  MT_CHECK_EQ(st.server_count(), size_t{2});
  st.track_resp(1, Phase::priority, 1);
  for (int i = 0; i <= 12; ++i) {
    fake_now = i;
    st.do_clean();
    if (i == 6) {
      // keep server 1 alive mid-window: new traffic moves the global
      // counter, then a request re-marks server 1 past the erase point
      st.track_resp(1, Phase::priority, 1);
      (void)st.get_req_params(1);
    }
  }
  MT_CHECK_EQ(st.server_count(), size_t{1});
  // self-heal: response from the forgotten server re-creates a record
  st.track_resp(2, Phase::priority, 1);
  MT_CHECK_EQ(st.server_count(), size_t{2});
}

MT_MAIN()
