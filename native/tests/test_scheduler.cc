// Native scheduler tests, mirroring the reference server suite
// (/root/reference/test/test_dmclock_server.cc) and the Python suite
// (tests/test_scheduler.py): virtual-time injection throughout, QoS
// ratio checks, AtLimit policies, delayed/immediate tag calc,
// anticipation, idle-reactivation, and GC timing with an injected
// clock.

#include <sys/resource.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include "dmclock/scheduler.h"
#include "microtest.h"

using namespace dmclock;

using Q = PullPriorityQueue<uint64_t, uint64_t>;
constexpr int64_t S = NS_PER_SEC;

static Q::Options opts(bool delayed = false,
                       AtLimit at = AtLimit::Wait,
                       int64_t anticipation = 0, unsigned k = 2) {
  Q::Options o;
  o.delayed_tag_calc = delayed;
  o.at_limit = at;
  o.anticipation_timeout_ns = anticipation;
  o.heap_branching = k;
  return o;
}

static std::map<uint64_t, ClientInfo> g_infos;
static ClientInfo info_of(const uint64_t& c) { return g_infos.at(c); }

MT_TEST(pull_weight_ratio) {
  // weight 1:2 serves 2:4 of 6 (reference pull_weight :822-874)
  g_infos = {{1, ClientInfo(0, 1, 0)}, {2, ClientInfo(0, 2, 0)}};
  for (unsigned k : {2u, 3u, 4u}) {
    Q q(info_of, opts(false, AtLimit::Wait, 0, k));
    int64_t t = 1 * S;
    for (uint64_t i = 0; i < 6; ++i) {
      q.add_request(100 + i, 1, ReqParams(), t);
      q.add_request(200 + i, 2, ReqParams(), t);
    }
    std::map<uint64_t, int> counts;
    for (int i = 0; i < 6; ++i) {
      auto pr = q.pull_request(t + S);
      MT_CHECK(pr.is_retn());
      MT_CHECK(pr.phase == Phase::priority);
      ++counts[pr.client];
    }
    MT_CHECK_EQ(counts[1], 2);
    MT_CHECK_EQ(counts[2], 4);
  }
}

MT_TEST(pull_reservation_ratio) {
  // reservation 2:1 serves 4:2 (reference pull_reservation :877-929)
  g_infos = {{1, ClientInfo(2, 0, 0)}, {2, ClientInfo(1, 0, 0)}};
  Q q(info_of, opts());
  int64_t t = 100 * S;
  for (uint64_t i = 0; i < 6; ++i) {
    q.add_request(100 + i, 1, ReqParams(), t);
    q.add_request(200 + i, 2, ReqParams(), t);
  }
  std::map<uint64_t, int> counts;
  for (int i = 0; i < 6; ++i) {
    auto pr = q.pull_request(t + 100 * S);
    MT_CHECK(pr.is_retn());
    MT_CHECK(pr.phase == Phase::reservation);
    ++counts[pr.client];
  }
  MT_CHECK_EQ(counts[1], 4);
  MT_CHECK_EQ(counts[2], 2);
}

MT_TEST(future_and_none) {
  g_infos = {{1, ClientInfo(1, 1, 1)}};
  Q q(info_of, opts());
  MT_CHECK(q.pull_request(1 * S).is_none());
  q.add_request(7, 1, ReqParams(), 10 * S);
  auto pr = q.pull_request(10 * S);
  MT_CHECK(pr.is_retn());
  MT_CHECK_EQ(pr.request, uint64_t{7});
  q.add_request(8, 1, ReqParams(), 10 * S);
  pr = q.pull_request(10 * S);
  MT_CHECK(pr.is_future());
  MT_CHECK_EQ(pr.when_ready, 11 * S);  // limited 1/s away
}

MT_TEST(delayed_tag_calc_matches_immediate_order) {
  // same workload under both modes yields the same service order when
  // rho/delta are constant (the modes differ only in WHEN tags compute)
  g_infos = {{1, ClientInfo(1, 2, 0)}, {2, ClientInfo(2, 1, 0)}};
  Q qi(info_of, opts(false)), qd(info_of, opts(true));
  int64_t t = 5 * S;
  for (uint64_t i = 0; i < 8; ++i) {
    for (uint64_t c : {1, 2}) {
      qi.add_request(c * 100 + i, c, ReqParams(1, 1), t + int64_t(i));
      qd.add_request(c * 100 + i, c, ReqParams(1, 1), t + int64_t(i));
    }
  }
  for (int i = 0; i < 16; ++i) {
    auto a = qi.pull_request(t + 60 * S);
    auto b = qd.pull_request(t + 60 * S);
    MT_CHECK(a.is_retn() && b.is_retn());
    MT_CHECK_EQ(a.client, b.client);
    MT_CHECK_EQ(a.request, b.request);
  }
}

MT_TEST(allow_limit_break) {
  g_infos = {{1, ClientInfo(0, 1, 1)}};
  Q q(info_of, opts(false, AtLimit::Allow));
  int64_t t = 50 * S;
  q.add_request(1, 1, ReqParams(), t);
  q.add_request(2, 1, ReqParams(), t);
  MT_CHECK(q.pull_request(t).is_retn());
  MT_CHECK(q.pull_request(t).is_retn());  // over-limit break
  MT_CHECK_EQ(q.limit_break_sched_count, uint64_t{1});
}

MT_TEST(reject_over_limit) {
  // Reject returns EAGAIN without taking ownership (reference :1301-1360)
  g_infos = {{1, ClientInfo(0, 1, 1)}};
  Q::Options o = opts(false, AtLimit::Reject);
  Q q(info_of, o);
  int64_t t = 50 * S;
  MT_CHECK_EQ(q.add_request(1, 1, ReqParams(), t), 0);
  MT_CHECK_EQ(q.add_request(2, 1, ReqParams(), t), EAGAIN);
  MT_CHECK_EQ(q.request_count(), uint64_t{1});
  // with a threshold, the next second of work is admitted
  Q::Options o2 = opts(false, AtLimit::Wait);
  o2.reject_threshold_ns = 1 * S;  // implies Reject (reference :89-93)
  Q q2(info_of, o2);
  MT_CHECK_EQ(q2.add_request(1, 1, ReqParams(), t), 0);
  MT_CHECK_EQ(q2.add_request(2, 1, ReqParams(), t), 0);
  MT_CHECK_EQ(q2.add_request(3, 1, ReqParams(), t), EAGAIN);
}

MT_TEST(anticipation_preserves_credit) {
  // an arrival within the anticipation window is backdated so a
  // briefly-idle client keeps its virtual-time credit (reference
  // :159-161); with it, client 1's second request still sorts first
  g_infos = {{1, ClientInfo(0, 1, 0)}, {2, ClientInfo(0, 1, 0)}};
  Q qa(info_of, opts(false, AtLimit::Wait, S / 2));
  int64_t t = 10 * S;
  qa.add_request(11, 1, ReqParams(), t);
  qa.add_request(21, 2, ReqParams(), t);
  auto p1 = qa.pull_request(t);
  MT_CHECK_EQ(p1.client, uint64_t{1});
  // client 1 idles 0.3 s (inside the window) then asks again
  qa.add_request(12, 1, ReqParams(), t + 3 * S / 10);
  auto p2 = qa.pull_request(t + 3 * S / 10);
  // backdating means client 1's proportion advanced from its previous
  // tag, not from wall time: client 2 (still at t) wins
  MT_CHECK_EQ(p2.client, uint64_t{2});
}

MT_TEST(update_client_info_applies) {
  // delayed mode: queued-but-untagged requests pick up the new info
  // when they reach the head (immediate mode tags at arrival, so an
  // info change cannot retro-affect already-queued work)
  g_infos = {{1, ClientInfo(0, 1, 0)}, {2, ClientInfo(0, 1, 0)}};
  Q q(info_of, opts(true));
  int64_t t = 5 * S;
  for (uint64_t i = 0; i < 6; ++i) {
    q.add_request(100 + i, 1, ReqParams(), t);
    q.add_request(200 + i, 2, ReqParams(), t);
  }
  (void)q.pull_request(t + S);
  g_infos[2].update(0, 4, 0);
  q.update_client_info(2);
  std::map<uint64_t, int> counts;
  for (int i = 0; i < 8; ++i) {
    auto pr = q.pull_request(t + S);
    if (pr.is_retn()) ++counts[pr.client];
  }
  MT_CHECK(counts[2] > counts[1]);
}

MT_TEST(remove_by_client_and_filter) {
  g_infos = {{1, ClientInfo(0, 1, 0)}, {2, ClientInfo(0, 1, 0)}};
  Q q(info_of, opts());
  int64_t t = 3 * S;
  for (uint64_t i = 0; i < 4; ++i) {
    q.add_request(100 + i, 1, ReqParams(), t);
    q.add_request(200 + i, 2, ReqParams(), t);
  }
  std::vector<uint64_t> got;
  q.remove_by_client(1, false, [&](uint64_t&& r) { got.push_back(r); });
  MT_CHECK_EQ(got.size(), size_t{4});
  MT_CHECK_EQ(got[0], uint64_t{100});
  bool removed = q.remove_by_req_filter(
      [](uint64_t&& r) { return r % 2 == 0; });
  MT_CHECK(removed);
  MT_CHECK_EQ(q.request_count(), uint64_t{2});
}

MT_TEST(prop_heap_matches_scan) {
  // The optional prop heap (reference USE_PROP_HEAP,
  // dmclock_server.h:18-25, :775-783) must be behaviorally invisible:
  // an identical op sequence -- including idle-reactivations, GC idle
  // marking, and erases -- produces the identical decision stream
  // with the O(1) lookup and the O(n) scan.
  g_infos.clear();
  const int N = 12;
  for (uint64_t c = 1; c <= N; ++c)
    g_infos[c] = ClientInfo(0.5 * (c % 3), 1.0 + c % 4,
                            c % 2 ? 0 : 8.0);
  for (bool gc_pass : {false, true}) {
    Q::Options oa = opts(true), ob = opts(true);
    ob.use_prop_heap = true;
    oa.idle_age_s = ob.idle_age_s = 10.0;
    oa.erase_age_s = ob.erase_age_s = 20.0;
    oa.check_time_s = ob.check_time_s = 1.0;
    Q qa(info_of, oa), qb(info_of, ob);
    double fake = 0.0;
    qa.set_monotonic_clock([&] { return fake; });
    qb.set_monotonic_clock([&] { return fake; });
    uint64_t seed = 12345, req = 0;
    auto rnd = [&] { seed = seed * 6364136223846793005ULL + 1442695040888963407ULL;
                     return seed >> 33; };
    int64_t t = 1 * S;
    for (int round = 0; round < 40; ++round) {
      // a random burst of adds (some clients go idle across rounds
      // and reactivate here, exercising the lookup under test)
      for (int i = 0; i < 6; ++i) {
        uint64_t c = 1 + rnd() % N;
        if (round > 10 && c <= 3) continue;  // 1-3 idle out
        ++req;
        MT_CHECK_EQ(qa.add_request(req, c, ReqParams(1, 1), t),
                    qb.add_request(req, c, ReqParams(1, 1), t));
      }
      for (int i = 0; i < 5; ++i) {
        auto pa = qa.pull_request(t + S);
        auto pb = qb.pull_request(t + S);
        MT_CHECK_EQ((int)pa.type, (int)pb.type);
        if (pa.is_retn()) {
          MT_CHECK_EQ(pa.client, pb.client);
          MT_CHECK_EQ((int)pa.phase, (int)pb.phase);
        }
      }
      t += S / 2;
      if (gc_pass) {
        fake += 1.0;
        qa.do_clean();
        qb.do_clean();
      }
    }
    MT_CHECK_EQ(qa.client_count(), qb.client_count());
  }
}

MT_TEST(gc_idle_then_erase) {
  // injected monotonic clock; timeline mirrors the reference's
  // client_idle_erase test (:100-185)
  g_infos = {{1, ClientInfo(1, 1, 0)}};
  double fake_now = 0.0;
  Q::Options o = opts();
  o.idle_age_s = 10.0;
  o.erase_age_s = 20.0;
  o.check_time_s = 1.0;
  Q q(info_of, o);
  q.set_monotonic_clock([&] { return fake_now; });
  q.add_request(1, 1, ReqParams(), 1 * S);
  (void)q.pull_request(2 * S);
  MT_CHECK_EQ(q.client_count(), uint64_t{1});
  for (int i = 0; i <= 30; ++i) {
    fake_now = i;
    q.do_clean();
  }
  MT_CHECK_EQ(q.client_count(), uint64_t{0});
}

MT_TEST(wait_at_limit_starvation_and_exact_future) {
  // Wait mode holds a limited client while unlimited work proceeds,
  // then reports the EXACT future wake-up time (reference
  // pull_wait_at_limit :1363-1471, exact `old_time + 2.0` at :1458).
  g_infos = {{1, ClientInfo(0, 1, 1)},    // A: weight 1, limit 1/s
             {2, ClientInfo(0, 1, 0)}};   // B: weight 1, no limit
  Q q(info_of, opts());
  int64_t t = 40 * S;
  for (uint64_t i = 0; i < 3; ++i) q.add_request(100 + i, 1,
                                                 ReqParams(), t);
  for (uint64_t i = 0; i < 3; ++i) q.add_request(200 + i, 2,
                                                 ReqParams(), t);
  // first pull: tags tie at t; A wins by creation order
  auto p = q.pull_request(t);
  MT_CHECK(p.is_retn());
  MT_CHECK_EQ(p.client, uint64_t{1});
  // A is now over-limit until t+1s; B drains meanwhile
  for (int i = 0; i < 3; ++i) {
    p = q.pull_request(t);
    MT_CHECK(p.is_retn());
    MT_CHECK_EQ(p.client, uint64_t{2});
  }
  p = q.pull_request(t);
  MT_CHECK(p.is_future());
  MT_CHECK_EQ(p.when_ready, t + 1 * S);
  p = q.pull_request(t + 1 * S);
  MT_CHECK(p.is_retn());
  MT_CHECK_EQ(p.client, uint64_t{1});
  p = q.pull_request(t + 1 * S);
  MT_CHECK(p.is_future());
  MT_CHECK_EQ(p.when_ready, t + 2 * S);
  p = q.pull_request(t + 2 * S);
  MT_CHECK(p.is_retn());
  MT_CHECK_EQ(p.client, uint64_t{1});
  MT_CHECK(q.pull_request(t + 2 * S).is_none());
}

MT_TEST(dynamic_cli_info_refetches_every_use) {
  // U1 axis (reference dynamic_cli_info_f :1021-1114): with
  // dynamic_cli_info the embedder callback is consulted on every use,
  // so a QoS change takes effect WITHOUT update_client_info.  Delayed
  // tag calc so queued-but-untagged requests pick the new info up as
  // they reach the head (immediate mode tags at arrival).
  g_infos = {{1, ClientInfo(0, 1, 0)}, {2, ClientInfo(0, 1, 0)}};
  Q::Options o = opts(true);
  o.dynamic_cli_info = true;
  Q q(info_of, o);
  int64_t t = 5 * S;
  for (uint64_t i = 0; i < 8; ++i) {
    q.add_request(100 + i, 1, ReqParams(), t);
    q.add_request(200 + i, 2, ReqParams(), t);
  }
  std::map<uint64_t, int> counts;
  for (int i = 0; i < 4; ++i) ++counts[q.pull_request(t + S).client];
  MT_CHECK_EQ(counts[1], 2);
  MT_CHECK_EQ(counts[2], 2);
  g_infos[2].update(0, 3, 0);     // no update_client_info call
  counts.clear();
  for (int i = 0; i < 8; ++i) ++counts[q.pull_request(t + S).client];
  MT_CHECK(counts[2] > counts[1]);
}

MT_TEST(remove_by_req_filter_visit_order) {
  // forward vs backwards traversal hands requests to the accumulator
  // in the documented order (reference remove_by_req_filter_ordering
  // :373-605)
  g_infos = {{1, ClientInfo(0, 1, 0)}};
  for (bool backwards : {false, true}) {
    Q q(info_of, opts());
    for (uint64_t i = 0; i < 6; ++i)
      q.add_request(100 + i, 1, ReqParams(), 2 * S);
    std::vector<uint64_t> got;
    q.remove_by_req_filter(
        [&](uint64_t&& r) { got.push_back(r); return true; },
        backwards);
    MT_CHECK_EQ(got.size(), size_t{6});
    MT_CHECK_EQ(got.front(), backwards ? uint64_t{105} : uint64_t{100});
    MT_CHECK_EQ(got.back(), backwards ? uint64_t{100} : uint64_t{105});
    MT_CHECK_EQ(q.request_count(), uint64_t{0});
    MT_CHECK(q.empty());
  }
  // reverse accumulation for remove_by_client (reference
  // remove_by_client :608-681)
  Q q(info_of, opts());
  for (uint64_t i = 0; i < 4; ++i)
    q.add_request(100 + i, 1, ReqParams(), 2 * S);
  std::vector<uint64_t> got;
  q.remove_by_client(1, true, [&](uint64_t&& r) { got.push_back(r); });
  MT_CHECK_EQ(got.front(), uint64_t{103});
  MT_CHECK_EQ(got.back(), uint64_t{100});
}

MT_TEST(ready_and_under_limit_phases) {
  // phase state machine (reference ready_and_under_limit :1120-1181):
  // a reservation client is served from the constraint phase while a
  // limited weight client alternates ready/waiting
  g_infos = {{1, ClientInfo(1, 0, 0)},    // R: reservation only
             {2, ClientInfo(0, 1, 1)}};   // W: weight 1, limit 1/s
  Q q(info_of, opts());
  int64_t t = 20 * S;
  for (uint64_t i = 0; i < 2; ++i) {
    q.add_request(100 + i, 1, ReqParams(), t);
    q.add_request(200 + i, 2, ReqParams(), t);
  }
  // R's first reservation tag is eligible at t: constraint phase
  auto p = q.pull_request(t);
  MT_CHECK(p.is_retn());
  MT_CHECK_EQ(p.client, uint64_t{1});
  MT_CHECK(p.phase == Phase::reservation);
  // weight phase serves W's first request (ready at arrival)
  p = q.pull_request(t);
  MT_CHECK(p.is_retn());
  MT_CHECK_EQ(p.client, uint64_t{2});
  MT_CHECK(p.phase == Phase::priority);
  // R's second reservation tag: t + 1s; W over-limit until t + 1s
  p = q.pull_request(t);
  MT_CHECK(p.is_future());
  MT_CHECK_EQ(p.when_ready, t + 1 * S);
  p = q.pull_request(t + 1 * S);
  MT_CHECK(p.is_retn());
  MT_CHECK_EQ(p.client, uint64_t{1});
  MT_CHECK(p.phase == Phase::reservation);
  p = q.pull_request(t + 1 * S);
  MT_CHECK(p.is_retn());
  MT_CHECK_EQ(p.client, uint64_t{2});
  MT_CHECK(p.phase == Phase::priority);
  MT_CHECK(q.pull_request(t + 1 * S).is_none());
}

// fork-based death check (the reference's gtest death tests,
// test_dmclock_server.cc:51-97, with dmcPrCtl.h's core-dump disable)
template <typename Fn>
static bool dies_with_abort(Fn fn) {
  pid_t pid = fork();
  if (pid == 0) {
    struct rlimit rl {0, 0};
    setrlimit(RLIMIT_CORE, &rl);  // no core files from expected aborts
    freopen("/dev/null", "w", stderr);
    fn();
    _exit(0);  // reached only if the invariant did NOT fire
  }
  int st = 0;
  waitpid(pid, &st, 0);
  return WIFSIGNALED(st) && WTERMSIG(st) == SIGABRT;
}

MT_TEST(death_zero_reservation_and_weight) {
  // a client with r=0 AND w=0 can never be scheduled: adding its
  // request must abort (reference test_dmclock_server.cc:51-75)
  g_infos = {{1, ClientInfo(0, 0, 1)}};
  MT_CHECK(dies_with_abort([] {
    Q q(info_of, opts());
    q.add_request(1, 1, ReqParams(), 1 * S);
  }));
}

MT_TEST(death_reject_with_delayed_calc) {
  // AtLimit::Reject needs accurate tags at add time; combining it with
  // DelayedTagCalc must abort (reference :856-857, death test :77-97)
  g_infos = {{1, ClientInfo(1, 1, 2)}};
  MT_CHECK(dies_with_abort([] {
    Q q(info_of, opts(/*delayed=*/true, AtLimit::Reject));
  }));
}

MT_TEST(display_queues_dump) {
  // debug dump: three sections, every client listed (oracle
  // display_queues layout; reference :676-697)
  g_infos = {{1, ClientInfo(0, 1, 0)}, {2, ClientInfo(0, 2, 0)}};
  Q q(info_of, opts());
  q.add_request(100, 1, ReqParams(), 1 * S);
  q.add_request(200, 2, ReqParams(), 1 * S);
  std::string dump = q.display_queues();
  MT_CHECK(dump.find("RESER: ") != std::string::npos);
  MT_CHECK(dump.find("LIMIT: ") != std::string::npos);
  MT_CHECK(dump.find("READY: ") != std::string::npos);
  MT_CHECK(dump.find("1:") != std::string::npos);
  MT_CHECK(dump.find("2:") != std::string::npos);
  MT_CHECK(dump.find("noreq") == std::string::npos);
}

// ---- push-mode queue (reference PushPriorityQueue :1504-1797) ------

using PushQ = PushPriorityQueue<uint64_t, uint64_t>;

template <typename Pred>
static bool wait_until(Pred pred, int timeout_ms = 5000) {
  for (int i = 0; i < timeout_ms / 5; ++i) {
    if (pred()) return true;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  return pred();
}

MT_TEST(push_immediate_dispatch) {
  g_infos = {{7, ClientInfo(0, 1, 0)}};
  std::mutex m;
  std::vector<std::pair<uint64_t, int>> handled;
  PushQ q(info_of, [] { return true; },
          [&](const uint64_t& c, uint64_t&&, Phase p, Cost) {
            std::lock_guard<std::mutex> g(m);
            handled.emplace_back(c, int(p));
          },
          opts());
  q.add_request(1, 7, ReqParams());
  MT_CHECK(wait_until([&] {
    std::lock_guard<std::mutex> g(m);
    return handled.size() == 1;
  }));
  std::lock_guard<std::mutex> g(m);
  if (handled.empty()) return;  // MT_CHECK above already failed
  MT_CHECK_EQ(handled[0].first, uint64_t{7});
  MT_CHECK_EQ(handled[0].second, int(Phase::priority));
}

MT_TEST(push_can_handle_gates) {
  g_infos = {{1, ClientInfo(0, 1, 0)}};
  std::atomic<bool> open{false};
  std::atomic<int> n{0};
  PushQ q(info_of, [&] { return open.load(); },
          [&](const uint64_t&, uint64_t&&, Phase, Cost) { ++n; },
          opts());
  q.add_request(1, 1, ReqParams());
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  MT_CHECK_EQ(n.load(), 0);
  open = true;
  q.request_completed();  // server signals capacity
  MT_CHECK(wait_until([&] { return n.load() == 1; }));
}

MT_TEST(push_sched_ahead_timed_wakeup) {
  // limit 10/s: the second request becomes eligible ~0.1s later and
  // must be dispatched by the sched-ahead thread unprompted
  g_infos = {{1, ClientInfo(0, 1, 10)}};
  std::atomic<int> n{0};
  PushQ q(info_of, [] { return true; },
          [&](const uint64_t&, uint64_t&&, Phase, Cost) { ++n; },
          opts());
  int64_t now = get_time_ns();
  q.add_request(1, 1, ReqParams(), now);
  q.add_request(2, 1, ReqParams(), now);
  MT_CHECK(wait_until([&] { return n.load() == 2; }));
}

MT_MAIN()
