// Minimal single-header test harness (gtest is not available in this
// environment; this provides the few primitives the suites need).

#pragma once

#include <cstdio>
#include <cstdlib>
#include <functional>
#include <iostream>
#include <string>
#include <vector>

namespace microtest {

struct Registry {
  static Registry& get() {
    static Registry r;
    return r;
  }
  std::vector<std::pair<std::string, std::function<void()>>> tests;
  int failures = 0;
  std::string current;
};

struct Register {
  Register(const char* name, std::function<void()> fn) {
    Registry::get().tests.emplace_back(name, std::move(fn));
  }
};

inline int run_all() {
  auto& reg = Registry::get();
  int ran = 0;
  for (auto& [name, fn] : reg.tests) {
    reg.current = name;
    int before = reg.failures;
    fn();
    ++ran;
    std::printf("[%s] %s\n",
                reg.failures == before ? "PASS" : "FAIL", name.c_str());
  }
  std::printf("%d tests, %d failures\n", ran, reg.failures);
  return reg.failures ? 1 : 0;
}

}  // namespace microtest

#define MT_TEST(name)                                            \
  static void mt_##name();                                       \
  static microtest::Register mt_reg_##name(#name, mt_##name);    \
  static void mt_##name()

#define MT_CHECK(cond)                                                 \
  do {                                                                 \
    if (!(cond)) {                                                     \
      ++microtest::Registry::get().failures;                           \
      std::printf("  CHECK failed: %s (%s:%d in %s)\n", #cond,         \
                  __FILE__, __LINE__,                                  \
                  microtest::Registry::get().current.c_str());         \
    }                                                                  \
  } while (0)

#define MT_CHECK_EQ(a, b)                                              \
  do {                                                                 \
    auto va = (a);                                                     \
    auto vb = (b);                                                     \
    if (!(va == vb)) {                                                 \
      ++microtest::Registry::get().failures;                           \
      std::cout << "  CHECK_EQ failed: " << #a << " (" << va           \
                << ") != " << #b << " (" << vb << ") at " << __FILE__  \
                << ":" << __LINE__ << "\n";                            \
    }                                                                  \
  } while (0)

#define MT_MAIN() \
  int main() { return microtest::run_all(); }
