// Indirect intrusive k-way heap tests.
//
// Covers the reference suite's ground
// (/root/reference/support/test/test_indirect_intrusive_heap.cc):
// ordering across K, promote/demote/adjust, the remove-then-sift-both-
// ways case, and one element living in two heaps via two index slots.

#include <algorithm>
#include <memory>
#include <random>
#include <sstream>
#include <vector>

#include "dmclock/indirect_heap.h"
#include "microtest.h"

using dmclock::HEAP_NOT_IN;
using dmclock::IndirectHeap;

struct Elem {
  int key = 0;
  int key2 = 0;
  size_t pos_a = HEAP_NOT_IN;
  size_t pos_b = HEAP_NOT_IN;
  explicit Elem(int k, int k2 = 0) : key(k), key2(k2) {}
};

struct CmpA {
  bool operator()(const Elem& x, const Elem& y) const { return x.key < y.key; }
};
struct CmpB {
  bool operator()(const Elem& x, const Elem& y) const {
    return x.key2 < y.key2;
  }
};

using HeapA = IndirectHeap<Elem, CmpA, &Elem::pos_a>;
using HeapB = IndirectHeap<Elem, CmpB, &Elem::pos_b>;

MT_TEST(push_pop_sorted_all_k) {
  std::mt19937 rng(42);
  for (unsigned k : {2u, 3u, 4u, 10u}) {
    HeapA h(k);
    std::vector<std::unique_ptr<Elem>> owner;
    std::vector<int> keys(200);
    for (int i = 0; i < 200; ++i) keys[i] = int(rng() % 1000);
    for (int v : keys) {
      owner.push_back(std::make_unique<Elem>(v));
      h.push(owner.back().get());
    }
    std::sort(keys.begin(), keys.end());
    for (int v : keys) {
      MT_CHECK_EQ(h.top().key, v);
      h.pop();
    }
    MT_CHECK(h.empty());
  }
}

MT_TEST(intrusive_index_tracks_position) {
  HeapA h(3);
  std::vector<std::unique_ptr<Elem>> owner;
  for (int v : {5, 1, 9, 3, 7}) {
    owner.push_back(std::make_unique<Elem>(v));
    h.push(owner.back().get());
  }
  for (auto& e : owner) {
    MT_CHECK(e->pos_a != HEAP_NOT_IN);
    MT_CHECK(&h.at(e->pos_a) == e.get());
  }
}

MT_TEST(adjust_promote_demote) {
  HeapA h(2);
  std::vector<std::unique_ptr<Elem>> owner;
  for (int v : {10, 20, 30, 40, 50}) {
    owner.push_back(std::make_unique<Elem>(v));
    h.push(owner.back().get());
  }
  owner[4]->key = 1;  // 50 -> 1
  h.promote(*owner[4]);
  MT_CHECK_EQ(h.top().key, 1);
  owner[4]->key = 99;
  h.demote(*owner[4]);
  MT_CHECK_EQ(h.top().key, 10);
  owner[0]->key = 25;  // adjust must sift whichever way is needed
  h.adjust(*owner[0]);
  MT_CHECK_EQ(h.top().key, 20);
}

MT_TEST(remove_middle_sifts_correctly) {
  // a remove whose replacement must sift up (the tricky case the
  // reference comments on at indirect_intrusive_heap.h:437-441)
  std::mt19937 rng(7);
  for (int trial = 0; trial < 50; ++trial) {
    HeapA h(2);
    std::vector<std::unique_ptr<Elem>> owner;
    for (int i = 0; i < 30; ++i) {
      owner.push_back(std::make_unique<Elem>(int(rng() % 100)));
      h.push(owner.back().get());
    }
    size_t kill = rng() % owner.size();
    int killed_key = owner[kill]->key;
    h.remove(*owner[kill]);
    MT_CHECK(owner[kill]->pos_a == HEAP_NOT_IN);
    std::vector<int> rest;
    for (size_t i = 0; i < owner.size(); ++i)
      if (i != kill) rest.push_back(owner[i]->key);
    std::sort(rest.begin(), rest.end());
    // drain must return everything except the removed, sorted
    for (int v : rest) {
      MT_CHECK_EQ(h.top().key, v);
      h.pop();
    }
    (void)killed_key;
  }
}

MT_TEST(two_heaps_one_element) {
  HeapA ha(2);
  HeapB hb(3);
  std::vector<std::unique_ptr<Elem>> owner;
  for (int i = 0; i < 10; ++i) {
    owner.push_back(std::make_unique<Elem>(i, 9 - i));
    ha.push(owner.back().get());
    hb.push(owner.back().get());
  }
  MT_CHECK_EQ(ha.top().key, 0);
  MT_CHECK_EQ(hb.top().key2, 0);
  MT_CHECK(&ha.top() == owner.front().get());
  MT_CHECK(&hb.top() == owner.back().get());
  // removing from one heap leaves the other intact
  ha.remove(*owner.front());
  MT_CHECK_EQ(ha.top().key, 1);
  MT_CHECK_EQ(hb.top().key2, 0);
}

MT_MAIN()

MT_TEST(cross_k_consistency_random_ops) {
  // The same random op sequence (push / pop / adjust / remove) must
  // yield the same pop order for every K -- unique keys make the order
  // total (reference cross-K suite,
  // test_indirect_intrusive_heap.cc:266-465).
  std::mt19937 rng(7);
  constexpr int kOps = 1500;
  // pre-generate the op tape so every K replays identical decisions
  struct Op { int kind; int a; int newkey; };
  std::vector<Op> tape(kOps);
  for (auto& op : tape)
    op = Op{int(rng() % 5), int(rng()), int(rng() % 1000000)};

  std::vector<std::vector<int>> popped_by_k;
  for (unsigned k : {2u, 3u, 4u, 7u, 10u}) {
    HeapA h(k);
    std::vector<std::unique_ptr<Elem>> owner;
    std::vector<Elem*> live;
    int next_key = 0;
    std::vector<int> popped;
    for (const auto& op : tape) {
      switch (op.kind < 2 ? 0 : op.kind - 1) {
        case 0: {  // push (2x weight) (unique ascending-scrambled key)
          owner.push_back(std::make_unique<Elem>(
              (op.newkey << 11) | (next_key++ & 0x7FF)));
          live.push_back(owner.back().get());
          h.push(owner.back().get());
          break;
        }
        case 1: {  // pop
          if (!h.empty()) {
            Elem* top = &h.top();
            popped.push_back(top->key);
            h.pop();
            live.erase(std::find(live.begin(), live.end(), top));
          }
          break;
        }
        case 2: {  // adjust: rewrite a live element's key
          if (!live.empty()) {
            Elem* e = live[size_t(op.a) % live.size()];
            e->key = (op.newkey << 11) | (next_key++ & 0x7FF);
            h.adjust(*e);
          }
          break;
        }
        case 3: {  // remove from the middle
          if (!live.empty()) {
            size_t i = size_t(op.a) % live.size();
            h.remove(*live[i]);
            live.erase(live.begin() + long(i));
          }
          break;
        }
      }
    }
    while (!h.empty()) {
      popped.push_back(h.top().key);
      h.pop();
    }
    popped_by_k.push_back(std::move(popped));
  }
  for (size_t i = 1; i < popped_by_k.size(); ++i)
    MT_CHECK(popped_by_k[i] == popped_by_k[0]);
  MT_CHECK(popped_by_k[0].size() > 100);  // enough coverage
}

MT_TEST(iteration_and_display_sorted) {
  // iterators walk raw storage; display_sorted emits ascending order
  // without disturbing the heap (reference iterators :68-203 and
  // display_sorted :399-424)
  HeapA h(3);
  std::vector<std::unique_ptr<Elem>> owner;
  std::mt19937 rng(5);
  std::vector<int> keys;
  for (int i = 0; i < 40; ++i) {
    keys.push_back(int(rng() % 500) * 2);  // even, distinct enough
    owner.push_back(std::make_unique<Elem>(keys.back()));
    h.push(owner.back().get());
  }
  // begin/end cover every element exactly once
  std::vector<int> seen;
  for (auto it = h.begin(); it != h.end(); ++it)
    seen.push_back((*it)->key);
  std::sort(seen.begin(), seen.end());
  std::vector<int> expect = keys;
  std::sort(expect.begin(), expect.end());
  MT_CHECK(seen == expect);
  // contains() reflects membership via the intrusive slot
  for (auto& e : owner) MT_CHECK(h.contains(*e));
  Elem outside(1);
  MT_CHECK(!h.contains(outside));
  // display_sorted: ascending, all elements, heap untouched
  std::ostringstream os;
  h.display_sorted(os, [](std::ostream& o, const Elem& e) {
    o << e.key << "\n";
  });
  std::istringstream in(os.str());
  std::vector<int> dumped;
  int v;
  while (in >> v) dumped.push_back(v);
  MT_CHECK(dumped == expect);
  MT_CHECK_EQ(h.size(), size_t{40});
  MT_CHECK_EQ(h.top().key, expect.front());
}

MT_TEST(search_surface_find_and_rfind) {
  // find (O(1) via the intrusive slot), find_if / rfind_if predicate
  // scans (reference indirect_intrusive_heap.h:68-203)
  HeapA h(2);
  std::vector<std::unique_ptr<Elem>> owner;
  for (int i = 0; i < 25; ++i) {
    owner.push_back(std::make_unique<Elem>(i * 3));
    h.push(owner.back().get());
  }
  // exact-element find returns the element's own storage slot
  for (auto& e : owner) {
    auto it = h.find(*e);
    MT_CHECK(it != h.end());
    MT_CHECK(*it == e.get());
  }
  Elem outside(999);
  MT_CHECK(h.find(outside) == h.end());
  // predicate find locates by key
  auto it = h.find_if([](const Elem& e) { return e.key == 36; });
  MT_CHECK(it != h.end());
  MT_CHECK_EQ((*it)->key, 36);
  // rfind_if agrees with find_if when the match is unique
  auto rit = h.rfind_if([](const Elem& e) { return e.key == 36; });
  MT_CHECK(rit != h.end());
  MT_CHECK(*rit == *it);
  // no match: both return end()
  MT_CHECK(h.find_if([](const Elem& e) { return e.key == 1; })
           == h.end());
  MT_CHECK(h.rfind_if([](const Elem& e) { return e.key == 1; })
           == h.end());
  // removal clears the slot, so find no longer returns it
  Elem* victim = owner[7].get();
  h.remove(*victim);
  MT_CHECK(h.find(*victim) == h.end());
  // rfind_if under DUPLICATES returns the LAST storage match (its
  // distinguishing behavior vs find_if)
  owner.push_back(std::make_unique<Elem>(36));   // second key==36
  h.push(owner.back().get());
  auto f1 = h.find_if([](const Elem& e) { return e.key == 36; });
  auto r1 = h.rfind_if([](const Elem& e) { return e.key == 36; });
  MT_CHECK(f1 != h.end());
  MT_CHECK(r1 != h.end());
  MT_CHECK(f1 <= r1);
  MT_CHECK((*r1)->key == 36 && (*f1)->key == 36);
  // they bracket the duplicate pair: no matching element lies after
  // r1 or before f1
  for (auto it2 = std::next(r1); it2 != h.end(); ++it2)
    MT_CHECK((*it2)->key != 36);
  for (auto it2 = h.begin(); it2 != f1; ++it2)
    MT_CHECK((*it2)->key != 36);
  // const searches compile and agree
  const HeapA& ch = h;
  MT_CHECK(ch.find(*owner.back()) != ch.end());
  MT_CHECK(ch.find_if([](const Elem& e) { return e.key == 36; })
           != ch.end());
}
