# CMake generated Testfile for 
# Source directory: /root/repo/native
# Build directory: /root/repo/native/build
# 
# This file includes the relevant testing commands required for 
# testing this directory and lists subdirectories to be tested as well.
add_test(heap "/root/repo/native/build/test_heap")
set_tests_properties(heap PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;41;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test(scheduler "/root/repo/native/build/test_scheduler")
set_tests_properties(scheduler PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;41;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test(tracker "/root/repo/native/build/test_tracker")
set_tests_properties(tracker PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;41;add_test;/root/repo/native/CMakeLists.txt;0;")
