file(REMOVE_RECURSE
  "CMakeFiles/test_heap.dir/tests/test_heap.cc.o"
  "CMakeFiles/test_heap.dir/tests/test_heap.cc.o.d"
  "test_heap"
  "test_heap.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_heap.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
