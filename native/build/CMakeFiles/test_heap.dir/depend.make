# Empty dependencies file for test_heap.
# This may be replaced when dependencies are built.
