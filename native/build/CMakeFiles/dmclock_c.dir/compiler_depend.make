# Empty compiler generated dependencies file for dmclock_c.
# This may be replaced when dependencies are built.
