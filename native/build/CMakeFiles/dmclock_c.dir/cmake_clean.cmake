file(REMOVE_RECURSE
  "CMakeFiles/dmclock_c.dir/src/capi.cc.o"
  "CMakeFiles/dmclock_c.dir/src/capi.cc.o.d"
  "libdmclock_c.pdb"
  "libdmclock_c.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/dmclock_c.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
