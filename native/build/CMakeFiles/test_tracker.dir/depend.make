# Empty dependencies file for test_tracker.
# This may be replaced when dependencies are built.
