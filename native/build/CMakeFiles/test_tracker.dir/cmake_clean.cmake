file(REMOVE_RECURSE
  "CMakeFiles/test_tracker.dir/tests/test_tracker.cc.o"
  "CMakeFiles/test_tracker.dir/tests/test_tracker.cc.o.d"
  "test_tracker"
  "test_tracker.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_tracker.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
