# Empty dependencies file for test_scheduler.
# This may be replaced when dependencies are built.
