file(REMOVE_RECURSE
  "CMakeFiles/test_scheduler.dir/tests/test_scheduler.cc.o"
  "CMakeFiles/test_scheduler.dir/tests/test_scheduler.cc.o.d"
  "test_scheduler"
  "test_scheduler.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_scheduler.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
