// CPython-compatible Mersenne Twister.
//
// The Python sim harness's only randomness is random.Random(seed)
// .randrange(n) consumed in event order (harness.py _make_server_select);
// replicating CPython's MT19937 seeding (init_by_array) and
// _randbelow_with_getrandbits draw-for-draw makes the native simulator's
// service trace BIT-IDENTICAL to the Python simulator's for the same
// seed -- the cross-language sim parity gate.  Algorithm constants are
// the published MT19937 reference (Matsumoto & Nishimura); the seeding
// path mirrors CPython Modules/_randommodule.c.

#pragma once

#include <cstdint>
#include <vector>

namespace qos_sim {

class PyMT19937 {
 public:
  explicit PyMT19937(uint64_t seed) {
    // CPython random.seed(int): key = abs(seed) as 32-bit LE chunks
    std::vector<uint32_t> key;
    if (seed == 0) key.push_back(0);
    while (seed) {
      key.push_back(static_cast<uint32_t>(seed & 0xffffffffu));
      seed >>= 32;
    }
    init_by_array(key);
  }

  uint32_t genrand() {
    if (idx_ >= N) generate();
    uint32_t y = mt_[idx_++];
    y ^= y >> 11;
    y ^= (y << 7) & 0x9d2c5680u;
    y ^= (y << 15) & 0xefc60000u;
    y ^= y >> 18;
    return y;
  }

  // CPython getrandbits(k) for k <= 32
  uint32_t getrandbits(int k) { return genrand() >> (32 - k); }

  // CPython _randbelow_with_getrandbits: rejection-sample bit_length(n)
  // bits until < n (consumes a data-dependent number of draws -- this
  // must match Python exactly, including for n == 1)
  uint32_t randrange(uint32_t n) {
    int k = bit_length(n);
    uint32_t r = getrandbits(k);
    while (r >= n) r = getrandbits(k);
    return r;
  }

 private:
  static constexpr int N = 624;
  uint32_t mt_[N];
  int idx_ = N;

  static int bit_length(uint32_t n) {
    int k = 0;
    while (n) {
      ++k;
      n >>= 1;
    }
    return k;
  }

  void init_genrand(uint32_t s) {
    mt_[0] = s;
    for (int i = 1; i < N; ++i)
      mt_[i] = 1812433253u * (mt_[i - 1] ^ (mt_[i - 1] >> 30)) + i;
    idx_ = N;
  }

  void init_by_array(const std::vector<uint32_t>& key) {
    init_genrand(19650218u);
    int i = 1, j = 0;
    int k = N > static_cast<int>(key.size()) ? N
                                             : static_cast<int>(key.size());
    for (; k; --k) {
      mt_[i] = (mt_[i] ^ ((mt_[i - 1] ^ (mt_[i - 1] >> 30)) * 1664525u)) +
               key[j] + j;
      ++i;
      ++j;
      if (i >= N) {
        mt_[0] = mt_[N - 1];
        i = 1;
      }
      if (j >= static_cast<int>(key.size())) j = 0;
    }
    for (k = N - 1; k; --k) {
      mt_[i] =
          (mt_[i] ^ ((mt_[i - 1] ^ (mt_[i - 1] >> 30)) * 1566083941u)) - i;
      ++i;
      if (i >= N) {
        mt_[0] = mt_[N - 1];
        i = 1;
      }
    }
    mt_[0] = 0x80000000u;
  }

  void generate() {
    constexpr uint32_t M = 397;
    constexpr uint32_t MATRIX_A = 0x9908b0dfu;
    constexpr uint32_t UPPER = 0x80000000u;
    constexpr uint32_t LOWER = 0x7fffffffu;
    for (int i = 0; i < N; ++i) {
      uint32_t y = (mt_[i] & UPPER) | (mt_[(i + 1) % N] & LOWER);
      mt_[i] = mt_[(i + M) % N] ^ (y >> 1);
      if (y & 1) mt_[i] ^= MATRIX_A;
    }
    idx_ = 0;
  }
};

}  // namespace qos_sim
