// Simple-scheduler (FIFO) baseline, native edition.
//
// Equivalent of the reference's ssched comparison scheduler
// (/root/reference/sim/src/ssched/ssched_server.h:35-192 SimpleQueue,
// ssched_client.h:25-49 no-op tracker) and the Python
// dmclock_tpu/sim/ssched.py: same add/pull surface as the dmclock
// queues so it drops into the same sim harness.

#pragma once

#include <deque>
#include <functional>

#include "dmclock/recs.h"
#include "dmclock/scheduler.h"

namespace qos_sim {

class NullServiceTracker {
 public:
  dmclock::ReqParams get_req_params(uint64_t /*server*/) {
    return dmclock::ReqParams(0, 0);
  }
  void track_resp(uint64_t /*server*/, dmclock::Phase /*phase*/,
                  dmclock::Cost /*cost*/ = 1) {}
};

// strict-FIFO queue with the pull AND push surfaces (reference
// ssched_server.h: pull_request :154, push schedule_request :184)
class SimpleQueue {
 public:
  using Decision = dmclock::PullReq<uint64_t, uint64_t>;
  using CanHandleFunc = std::function<bool()>;
  using HandleFunc = std::function<void(uint64_t client, uint64_t request,
                                        dmclock::Phase, dmclock::Cost)>;

  SimpleQueue() = default;
  SimpleQueue(CanHandleFunc can_handle, HandleFunc handle)
      : can_handle_(std::move(can_handle)), handle_(std::move(handle)) {}

  int add_request(uint64_t request, const uint64_t& client,
                  const dmclock::ReqParams& /*params*/, int64_t /*time_ns*/,
                  dmclock::Cost cost = 1) {
    queue_.push_back(Entry{client, request, cost});
    if (handle_) schedule_request();
    return 0;
  }

  // -- push mode -----------------------------------------------------
  void request_completed() {
    if (handle_) schedule_request();
  }

  // FIFO never defers (no FUTURE decisions), so the sched-ahead seam
  // is a no-op; present so the push sim server template instantiates
  void sched_ahead_fire() {}

  void schedule_request() {
    // at most ONE dispatch per call (reference pacing: one request per
    // add/completion event, ssched_server.h:184-191)
    if (!queue_.empty() && (!can_handle_ || can_handle_())) {
      Entry e = queue_.front();
      queue_.pop_front();
      handle_(e.client, e.request, dmclock::Phase::priority, e.cost);
    }
  }

  Decision pull_request(int64_t /*now_ns*/) {
    Decision d;
    if (queue_.empty()) {
      d.type = dmclock::NextReqType::none;
      return d;
    }
    Entry e = queue_.front();
    queue_.pop_front();
    d.type = dmclock::NextReqType::returning;
    d.client = e.client;
    d.request = e.request;
    d.phase = dmclock::Phase::priority;
    d.cost = e.cost;
    return d;
  }

  size_t request_count() const { return queue_.size(); }
  bool empty() const { return queue_.empty(); }

 private:
  struct Entry {
    uint64_t client;
    uint64_t request;
    dmclock::Cost cost;
  };
  std::deque<Entry> queue_;
  CanHandleFunc can_handle_;
  HandleFunc handle_;
};

}  // namespace qos_sim
