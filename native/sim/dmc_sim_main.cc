// dmc_sim -- native dmClock QoS simulator binary.
//
// Equivalent of the reference simulator (/root/reference/sim/src/
// test_dmclock_main.cc:46-342) over this framework's native scheduler
// and discrete-event harness: reads the same INI config format, runs
// the closed-loop multi-server multi-client simulation, prints the
// report tables (and optionally the full service trace, which is
// bit-compared against the Python sim by tests/test_native_sim.py).
//
// Usage: dmc_sim -c CONF [--model dmclock|dmclock-delayed|ssched]
//                [--server-mode pull|push] [--seed N] [--k-way K]
//                [--intervals] [--trace]

#include <cstdio>
#include <cstring>
#include <memory>
#include <string>

#include "dmclock/scheduler.h"
#include "dmclock/tracker.h"
#include "sim_harness.h"
#include "ssched.h"

namespace {

using qos_sim::ClientId;
using qos_sim::ReqId;
using qos_sim::ServerId;
using qos_sim::SimConfig;

using DmcQueue = dmclock::PullPriorityQueue<ClientId, ReqId>;
using DmcPushQueue = dmclock::PushPriorityQueue<ClientId, ReqId>;
using DmcTracker = dmclock::ServiceTracker<ServerId>;

struct Args {
  std::string conf;
  std::string model = "dmclock";
  std::string server_mode = "pull";
  uint64_t seed = 12345;
  unsigned k_way = 2;  // heap branching (reference K_WAY_HEAP,
                       // sim/CMakeLists.txt:1-10 -- runtime here)
  bool use_prop_heap = false;  // reference USE_PROP_HEAP analog
  bool intervals = false;
  bool trace = false;
};

int usage(const char* prog) {
  fprintf(stderr,
          "usage: %s -c CONF [--model dmclock|dmclock-delayed|ssched] "
          "[--server-mode pull|push] [--seed N] [--k-way K] "
          "[--use-prop-heap] [--intervals] [--trace]\n",
          prog);
  return 2;
}

template <typename Sim>
int finish(Sim& sim, const Args& args) {
  sim.run();
  printf("%s", sim.report(args.intervals).c_str());
  if (args.trace) {
    for (const auto& op : sim.trace)
      printf("TRACE %lld %llu %llu %d %u\n", (long long)op.t_ns,
             (unsigned long long)op.server, (unsigned long long)op.client,
             op.phase, op.cost);
  }
  return 0;
}

static DmcQueue::Options make_opts(bool delayed, unsigned k_way,
                                   int64_t anticipation_ns,
                                   bool soft_limit,
                                   bool use_prop_heap) {
  DmcQueue::Options opt;
  opt.delayed_tag_calc = delayed;
  opt.heap_branching = k_way;
  opt.use_prop_heap = use_prop_heap;
  // soft limit -> Allow, hard -> Wait (reference
  // test_dmclock_main.cc:190-198 create_queue_f)
  opt.at_limit = soft_limit ? dmclock::AtLimit::Allow
                            : dmclock::AtLimit::Wait;
  opt.anticipation_timeout_ns = anticipation_ns;
  opt.run_gc_thread = false;
  return opt;
}

int run_dmclock(const SimConfig& cfg, const Args& args, bool delayed) {
  unsigned k_way = args.k_way;
  bool prop_heap = args.use_prop_heap;
  if (args.server_mode == "push") {
    qos_sim::Simulation<DmcPushQueue, DmcTracker> sim(
        cfg, nullptr, [] { return std::make_unique<DmcTracker>(); },
        args.seed, args.trace,
        [delayed, k_way, prop_heap](
            ServerId,
            std::function<dmclock::ClientInfo(const ClientId&)> info_f,
            int64_t anticipation_ns, bool soft_limit,
            std::function<bool()> can_handle,
            std::function<void(const ClientId&, ReqId&&, dmclock::Phase,
                               dmclock::Cost)>
                handle,
            std::function<int64_t()> now_f,
            std::function<void(int64_t)> sched_at) {
          return std::make_unique<DmcPushQueue>(
              std::move(info_f), std::move(can_handle),
              std::move(handle), std::move(now_f), std::move(sched_at),
              make_opts(delayed, k_way, anticipation_ns, soft_limit,
                        prop_heap));
        });
    return finish(sim, args);
  }
  qos_sim::Simulation<DmcQueue, DmcTracker> sim(
      cfg,
      [delayed, k_way, prop_heap](
          ServerId,
          std::function<dmclock::ClientInfo(const ClientId&)> info_f,
          int64_t anticipation_ns, bool soft_limit) {
        return std::make_unique<DmcQueue>(
            std::move(info_f),
            make_opts(delayed, k_way, anticipation_ns, soft_limit,
                      prop_heap));
      },
      [] { return std::make_unique<DmcTracker>(); }, args.seed,
      args.trace);
  return finish(sim, args);
}

int run_ssched(const SimConfig& cfg, const Args& args) {
  using SQ = qos_sim::SimpleQueue;
  if (args.server_mode == "push") {
    qos_sim::Simulation<SQ, qos_sim::NullServiceTracker> sim(
        cfg, nullptr,
        [] { return std::make_unique<qos_sim::NullServiceTracker>(); },
        args.seed, args.trace,
        [](ServerId,
           std::function<dmclock::ClientInfo(const ClientId&)>, int64_t,
           bool, std::function<bool()> can_handle,
           std::function<void(const ClientId&, ReqId&&, dmclock::Phase,
                              dmclock::Cost)>
               handle,
           std::function<int64_t()>, std::function<void(int64_t)>) {
          return std::make_unique<SQ>(std::move(can_handle),
                                      std::move(handle));
        });
    return finish(sim, args);
  }
  qos_sim::Simulation<SQ, qos_sim::NullServiceTracker> sim(
      cfg,
      [](ServerId,
         std::function<dmclock::ClientInfo(const ClientId&)>,
         int64_t, bool) { return std::make_unique<SQ>(); },
      [] { return std::make_unique<qos_sim::NullServiceTracker>(); },
      args.seed, args.trace);
  return finish(sim, args);
}

}  // namespace

int main(int argc, char** argv) {
  Args args;
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "-c") || !strcmp(argv[i], "--conf")) {
      if (++i >= argc) return usage(argv[0]);
      args.conf = argv[i];
    } else if (!strcmp(argv[i], "--model")) {
      if (++i >= argc) return usage(argv[0]);
      args.model = argv[i];
    } else if (!strcmp(argv[i], "--seed")) {
      if (++i >= argc) return usage(argv[0]);
      args.seed = strtoull(argv[i], nullptr, 10);
    } else if (!strcmp(argv[i], "--server-mode")) {
      if (++i >= argc) return usage(argv[0]);
      args.server_mode = argv[i];
      if (args.server_mode != "pull" && args.server_mode != "push")
        return usage(argv[0]);
    } else if (!strcmp(argv[i], "--k-way")) {
      if (++i >= argc) return usage(argv[0]);
      args.k_way = (unsigned)strtoul(argv[i], nullptr, 10);
    } else if (!strcmp(argv[i], "--use-prop-heap")) {
      args.use_prop_heap = true;
    } else if (!strcmp(argv[i], "--intervals")) {
      args.intervals = true;
    } else if (!strcmp(argv[i], "--trace")) {
      args.trace = true;
    } else {
      return usage(argv[0]);
    }
  }

  SimConfig cfg;
  if (!args.conf.empty()) {
    try {
      cfg = qos_sim::parse_config_file(args.conf);
    } catch (const std::exception& e) {
      fprintf(stderr, "dmc_sim: %s\n", e.what());
      return 2;
    }
  } else {
    cfg.fill_defaults();
  }

  if (args.model == "dmclock") return run_dmclock(cfg, args, false);
  if (args.model == "dmclock-delayed") return run_dmclock(cfg, args, true);
  if (args.model == "ssched") return run_ssched(cfg, args);
  fprintf(stderr, "dmc_sim: unknown model %s\n", args.model.c_str());
  return 2;
}
