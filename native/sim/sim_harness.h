// Discrete-event QoS simulation harness, native edition.
//
// Line-for-line behavioral mirror of the Python harness
// (dmclock_tpu/sim/harness.py), which is itself the framework's
// redesign of the reference's thread-sleep simulator
// (/root/reference/sim/src/simulate.h, sim_server.h, sim_client.h):
// virtual int64-ns clock, (time, seq)-ordered event heap, closed-loop
// rate-limited clients, thread-slot servers.  Because event scheduling
// and RNG consumption (pymt19937.h) happen in the same order as the
// Python sim, the service trace is bit-identical across languages for
// the same config+seed -- enforced by tests/test_native_sim.py.

#pragma once

#include <cassert>
#include <chrono>
#include <cstdint>
#include <functional>
#include <memory>
#include <queue>
#include <set>
#include <string>
#include <type_traits>
#include <vector>

#include "dmclock/profile.h"
#include "dmclock/recs.h"
#include "dmclock/scheduler.h"
#include "pymt19937.h"
#include "sim_config.h"

namespace qos_sim {

constexpr int64_t NS_PER_SEC = 1000000000;

using dmclock::Phase;
using dmclock::ProfileTimer;
using dmclock::ReqParams;

using ClientId = uint64_t;
using ServerId = uint64_t;
using ReqId = uint64_t;  // (client << 32) | send-seq
using Decision = dmclock::PullReq<ClientId, ReqId>;

// ---------------------------------------------------------------------
// event loop (harness.py EventLoop)
// ---------------------------------------------------------------------

class EventLoop {
 public:
  int64_t now_ns = 0;

  void at(int64_t t, std::function<void()> fn) {
    assert(t >= now_ns && "scheduling into the past");
    heap_.push(Event{t, seq_++, std::move(fn)});
  }
  void after(int64_t delay, std::function<void()> fn) {
    at(now_ns + delay, std::move(fn));
  }

  void run() {
    while (!heap_.empty()) {
      Event e = heap_.top();
      heap_.pop();
      now_ns = e.t;
      e.fn();
    }
  }

 private:
  struct Event {
    int64_t t;
    uint64_t seq;
    std::function<void()> fn;
  };
  struct Cmp {
    bool operator()(const Event& a, const Event& b) const {
      return a.t > b.t || (a.t == b.t && a.seq > b.seq);
    }
  };
  std::priority_queue<Event, std::vector<Event>, Cmp> heap_;
  uint64_t seq_ = 0;
};

// ---------------------------------------------------------------------
// trace record (harness.py SimulatedServer._start_service)
// ---------------------------------------------------------------------

struct TraceOp {
  int64_t t_ns;
  ServerId server;
  ClientId client;
  int phase;
  uint32_t cost;
};

// ---------------------------------------------------------------------
// server (harness.py SimulatedServer; reference sim_server.h:31-242)
// ---------------------------------------------------------------------

struct ServerStats {
  uint64_t ops_completed = 0;
  uint64_t reservation_ops = 0;
  uint64_t priority_ops = 0;
  ProfileTimer add_request_timer;
  ProfileTimer request_complete_timer;
};

// does the queue type expose the push surface (handle_f dispatch +
// sched_ahead_fire)?  Guards template instantiation so pull-only queue
// types never reference push members and vice versa.
template <typename Q, typename = void>
struct has_push_surface : std::false_type {};
template <typename Q>
struct has_push_surface<
    Q, std::void_t<decltype(std::declval<Q&>().sched_ahead_fire())>>
    : std::true_type {};

template <typename Q, typename = void>
struct has_pull_surface : std::false_type {};
template <typename Q>
struct has_pull_surface<
    Q, std::void_t<decltype(std::declval<Q&>().pull_request(int64_t{}))>>
    : std::true_type {};

// drive-mode-agnostic server surface (the harness only posts and reads
// stats), so pull and push servers mix behind one Simulation
struct ISimServer {
  virtual ~ISimServer() = default;
  virtual void post(ReqId request, ClientId client, const ReqParams& rp,
                    uint32_t cost) = 0;
  ServerStats stats;
};

using ClientRespF =
    std::function<void(ClientId, ReqId, Phase, uint32_t, ServerId)>;

template <typename Queue>
class SimulatedServer : public ISimServer {
 public:

  SimulatedServer(ServerId id, double iops, int threads,
                  std::unique_ptr<Queue> queue, EventLoop* loop,
                  ClientRespF client_resp_f, std::vector<TraceOp>* trace)
      : id_(id),
        threads_(threads),
        // reference rounds op time to whole microseconds
        // (sim_server.h:137-139)
        op_time_ns_(static_cast<int64_t>(0.5 + threads * 1e6 / iops) * 1000),
        queue_(std::move(queue)),
        loop_(loop),
        client_resp_f_(std::move(client_resp_f)),
        trace_(trace) {}

  void post(ReqId request, ClientId client, const ReqParams& rp,
            uint32_t cost) override {
    stats.add_request_timer.start();
    queue_->add_request(request, client, rp, loop_->now_ns, cost);
    stats.add_request_timer.stop();
    dispatch();
  }

  Queue& queue() { return *queue_; }

 private:
  void dispatch() {
    while (busy_ < threads_) {
      Decision pr = queue_->pull_request(loop_->now_ns);
      if (pr.is_retn()) {
        ++busy_;
        start_service(pr);
      } else if (pr.is_future()) {
        int64_t when = pr.when_ready;
        if (!wake_armed_ || when < wake_at_) {
          wake_armed_ = true;
          wake_at_ = when;
          int64_t t = when > loop_->now_ns ? when : loop_->now_ns;
          loop_->at(t, [this] { wake(); });
        }
        break;
      } else {
        break;
      }
    }
  }

  void wake() {
    wake_armed_ = false;
    dispatch();
  }

  void start_service(const Decision& pr) {
    if (trace_)
      trace_->push_back(TraceOp{loop_->now_ns, id_, pr.client,
                                static_cast<int>(pr.phase), pr.cost});
    ++stats.ops_completed;
    if (pr.phase == Phase::reservation)
      ++stats.reservation_ops;
    else
      ++stats.priority_ops;
    ClientId client = pr.client;
    ReqId request = pr.request;
    Phase phase = pr.phase;
    uint32_t cost = pr.cost;
    loop_->after(op_time_ns_ * cost, [this, client, request, phase, cost] {
      --busy_;
      client_resp_f_(client, request, phase, cost, id_);
      stats.request_complete_timer.start();
      // (push-mode queues would get request_completed() here)
      stats.request_complete_timer.stop();
      dispatch();
    });
  }

  ServerId id_;
  int threads_;
  int64_t op_time_ns_;
  std::unique_ptr<Queue> queue_;
  EventLoop* loop_;
  ClientRespF client_resp_f_;
  std::vector<TraceOp>* trace_;
  int busy_ = 0;
  bool wake_armed_ = false;
  int64_t wake_at_ = 0;
};

// ---------------------------------------------------------------------
// push-mode server (harness.py PushSimulatedServer): the QUEUE drives
// dispatch through handle_f -- the mode the reference's dmc_sim runs
// (test_dmclock.h:38-56).  One dispatch per trigger; with threads == 1
// the decision stream equals the pull server's.
// ---------------------------------------------------------------------

template <typename Queue>
class PushSimulatedServer : public ISimServer {
 public:
  // make_queue(can_handle_f, handle_f, now_f, sched_at_f)
  using MakeQueueF = std::function<std::unique_ptr<Queue>(
      std::function<bool()>,
      std::function<void(const ClientId&, ReqId&&, Phase, uint32_t)>,
      std::function<int64_t()>, std::function<void(int64_t)>)>;

  PushSimulatedServer(ServerId id, double iops, int threads,
                      const MakeQueueF& make_queue, EventLoop* loop,
                      ClientRespF client_resp_f,
                      std::vector<TraceOp>* trace)
      : id_(id),
        threads_(threads),
        op_time_ns_(static_cast<int64_t>(0.5 + threads * 1e6 / iops) *
                    1000),
        loop_(loop),
        client_resp_f_(std::move(client_resp_f)),
        trace_(trace) {
    queue_ = make_queue(
        [this] { return busy_ < threads_; },
        [this](const ClientId& c, ReqId&& r, Phase p, uint32_t cost) {
          handle(c, std::move(r), p, cost);
        },
        [this] { return loop_->now_ns; },
        [this](int64_t when) {
          int64_t t = when > loop_->now_ns ? when : loop_->now_ns;
          loop_->at(t, [this] { queue_->sched_ahead_fire(); });
        });
  }

  void post(ReqId request, ClientId client, const ReqParams& rp,
            uint32_t cost) override {
    stats.add_request_timer.start();
    queue_->add_request(request, client, rp, loop_->now_ns, cost);
    stats.add_request_timer.stop();
  }

  Queue& queue() { return *queue_; }

 private:
  // invoked BY the queue (under its lock) when it dispatches
  void handle(ClientId client, ReqId request, Phase phase,
              uint32_t cost) {
    ++busy_;
    if (trace_)
      trace_->push_back(TraceOp{loop_->now_ns, id_, client,
                                static_cast<int>(phase), cost});
    ++stats.ops_completed;
    if (phase == Phase::reservation)
      ++stats.reservation_ops;
    else
      ++stats.priority_ops;
    loop_->after(op_time_ns_ * cost,
                 [this, client, request, phase, cost] {
                   --busy_;
                   client_resp_f_(client, request, phase, cost, id_);
                   stats.request_complete_timer.start();
                   queue_->request_completed();
                   stats.request_complete_timer.stop();
                 });
  }

  ServerId id_;
  int threads_;
  int64_t op_time_ns_;
  std::unique_ptr<Queue> queue_;
  EventLoop* loop_;
  ClientRespF client_resp_f_;
  std::vector<TraceOp>* trace_;
  int busy_ = 0;
};

// ---------------------------------------------------------------------
// client (harness.py SimulatedClient; reference sim_client.h:76-336)
// ---------------------------------------------------------------------

struct ClientStats {
  uint64_t ops_requested = 0;
  uint64_t ops_completed = 0;
  uint64_t reservation_ops = 0;
  uint64_t priority_ops = 0;
  std::vector<int64_t> completion_times_ns;
  int64_t finish_time_ns = -1;
  ProfileTimer get_req_params_timer;
  ProfileTimer track_resp_timer;
};

template <typename Tracker>
class SimulatedClient {
 public:
  using SelectF = std::function<ServerId(int)>;
  using SubmitF =
      std::function<void(ServerId, ReqId, ClientId, const ReqParams&,
                         uint32_t)>;
  using DoneF = std::function<void(ClientId)>;

  SimulatedClient(ClientId id, const ClientGroup& g,
                  std::unique_ptr<Tracker> tracker, EventLoop* loop,
                  SelectF select, SubmitF submit, DoneF on_done)
      : id_(id),
        tracker_(std::move(tracker)),
        loop_(loop),
        select_(std::move(select)),
        submit_(std::move(submit)),
        on_done_(std::move(on_done)),
        // reference rounds the gap to whole microseconds
        // (sim_client.h:66-68)
        gap_ns_(static_cast<int64_t>(0.5 + 1e6 / g.client_iops_goal) * 1000),
        total_ops_(g.client_total_ops),
        max_outstanding_(g.client_outstanding_ops),
        cost_(g.client_req_cost) {
    loop_->at(static_cast<int64_t>(g.client_wait_s * NS_PER_SEC),
              [this] { attempt_send(); });
  }

  void receive_response(ReqId /*request*/, Phase phase, uint32_t cost,
                        ServerId server) {
    stats.track_resp_timer.start();
    tracker_->track_resp(server, phase, cost);
    stats.track_resp_timer.stop();
    --outstanding_;
    ++stats.ops_completed;
    if (phase == Phase::reservation)
      ++stats.reservation_ops;
    else
      ++stats.priority_ops;
    stats.completion_times_ns.push_back(loop_->now_ns);
    if (window_blocked_) {
      window_blocked_ = false;
      attempt_send();
    }
    if (sent_ >= total_ops_ && outstanding_ == 0) {
      stats.finish_time_ns = loop_->now_ns;
      on_done_(id_);
    }
  }

  ClientStats stats;

 private:
  void attempt_send() {
    if (sent_ >= total_ops_) return;
    if (outstanding_ >= max_outstanding_) {
      window_blocked_ = true;
      return;
    }
    ServerId server = select_(sent_);
    stats.get_req_params_timer.start();
    ReqParams rp = tracker_->get_req_params(server);
    stats.get_req_params_timer.stop();
    ReqId req = (id_ << 32) | static_cast<uint32_t>(sent_);
    submit_(server, req, id_, rp, cost_);
    ++sent_;
    ++outstanding_;
    ++stats.ops_requested;
    if (sent_ < total_ops_)
      loop_->after(gap_ns_, [this] { attempt_send(); });
  }

  ClientId id_;
  std::unique_ptr<Tracker> tracker_;
  EventLoop* loop_;
  SelectF select_;
  SubmitF submit_;
  DoneF on_done_;
  int64_t gap_ns_;
  int total_ops_;
  int max_outstanding_;
  uint32_t cost_;
  int outstanding_ = 0;
  int sent_ = 0;
  bool window_blocked_ = false;
};

// ---------------------------------------------------------------------
// simulation orchestrator (harness.py Simulation; reference
// simulate.h:33-445)
// ---------------------------------------------------------------------

template <typename Queue, typename Tracker>
class Simulation {
 public:
  using QueueFactory = std::function<std::unique_ptr<Queue>(
      ServerId, std::function<dmclock::ClientInfo(const ClientId&)>,
      int64_t anticipation_ns, bool soft_limit)>;
  using TrackerFactory = std::function<std::unique_ptr<Tracker>()>;

  // push-mode queue factory: like QueueFactory plus the four server
  // callbacks (can_handle, handle, now, sched_at)
  using PushQueueFactory = std::function<std::unique_ptr<Queue>(
      ServerId, std::function<dmclock::ClientInfo(const ClientId&)>,
      int64_t, bool, std::function<bool()>,
      std::function<void(const ClientId&, ReqId&&, Phase, uint32_t)>,
      std::function<int64_t()>, std::function<void(int64_t)>)>;

  Simulation(const SimConfig& cfg, QueueFactory queue_factory,
             TrackerFactory tracker_factory, uint64_t seed,
             bool record_trace,
             PushQueueFactory push_queue_factory = nullptr)
      : cfg_(cfg), rng_(seed),
        push_queue_factory_(std::move(push_queue_factory)) {
    if (record_trace) trace_ptr_ = &trace;

    for (size_t gi = 0; gi < cfg_.cli_group.size(); ++gi)
      for (int i = 0; i < cfg_.cli_group[gi].client_count; ++i)
        client_group_of_.push_back(static_cast<int>(gi));
    n_clients_ = static_cast<int>(client_group_of_.size());

    for (size_t gi = 0; gi < cfg_.srv_group.size(); ++gi)
      for (int i = 0; i < cfg_.srv_group[gi].server_count; ++i)
        server_group_of_.push_back(static_cast<int>(gi));
    n_servers_ = static_cast<int>(server_group_of_.size());

    for (auto& g : cfg_.cli_group)
      infos_.emplace_back(g.client_reservation, g.client_weight,
                          g.client_limit);

    auto info_f = [this](const ClientId& c) {
      return infos_[client_group_of_[c]];
    };

    int64_t anticipation_ns =
        static_cast<int64_t>(cfg_.anticipation_timeout_s * NS_PER_SEC);
    for (int s = 0; s < n_servers_; ++s) {
      auto& g = cfg_.srv_group[server_group_of_[s]];
      if (push_queue_factory_) {
        if constexpr (!has_push_surface<Queue>::value) {
          fprintf(stderr, "sim: queue type has no push surface\n");
          abort();
        } else {
        auto mk = [this, s, info_f, anticipation_ns](
                      std::function<bool()> can_handle,
                      std::function<void(const ClientId&, ReqId&&, Phase,
                                         uint32_t)>
                          handle,
                      std::function<int64_t()> now_f,
                      std::function<void(int64_t)> sched_at) {
          return push_queue_factory_(
              s, info_f, anticipation_ns, cfg_.server_soft_limit,
              std::move(can_handle), std::move(handle),
              std::move(now_f), std::move(sched_at));
        };
        servers_.push_back(std::make_unique<PushSimulatedServer<Queue>>(
            s, g.server_iops, g.server_threads, mk, &loop_,
            [this](ClientId c, ReqId r, Phase p, uint32_t cost,
                   ServerId sv) {
              clients_[c]->receive_response(r, p, cost, sv);
            },
            trace_ptr_));
        }
      } else {
        if constexpr (!has_pull_surface<Queue>::value) {
          fprintf(stderr, "sim: queue type has no pull surface\n");
          abort();
        } else {
        servers_.push_back(std::make_unique<SimulatedServer<Queue>>(
            s, g.server_iops, g.server_threads,
            queue_factory(s, info_f, anticipation_ns,
                          cfg_.server_soft_limit),
            &loop_,
            [this](ClientId c, ReqId r, Phase p, uint32_t cost,
                   ServerId sv) {
              clients_[c]->receive_response(r, p, cost, sv);
            },
            trace_ptr_));
        }
      }
    }

    for (int c = 0; c < n_clients_; ++c) {
      auto& g = cfg_.cli_group[client_group_of_[c]];
      clients_.push_back(std::make_unique<SimulatedClient<Tracker>>(
          c, g, tracker_factory(), &loop_, make_server_select(c, g),
          [this](ServerId s, ReqId r, ClientId c2, const ReqParams& rp,
                 uint32_t cost) { servers_[s]->post(r, c2, rp, cost); },
          [this](ClientId c2) { done_.insert(c2); }));
    }
  }

  void run() {
    auto t0 = std::chrono::steady_clock::now();
    loop_.run();
    wall_seconds_ = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() - t0)
                        .count();
    assert(static_cast<int>(done_.size()) == n_clients_ &&
           "not all clients finished");
  }

  // -- report (harness.py SimReport.format) ---------------------------
  std::string report(bool show_intervals = false) const {
    std::ostringstream os;
    uint64_t total = 0, res = 0, prop = 0;
    for (auto& c : clients_) {
      total += c->stats.ops_completed;
      res += c->stats.reservation_ops;
      prop += c->stats.priority_ops;
    }
    os << "=== simulation report ===\n";
    os << "clients: " << n_clients_ << "  servers: " << n_servers_ << "\n";
    char buf[160];
    snprintf(buf, sizeof buf,
             "virtual duration: %.3f s; wall: %.3f s\n",
             loop_.now_ns / double(NS_PER_SEC), wall_seconds_);
    os << buf;
    os << "total ops: " << total << " (reservation " << res
       << ", priority " << prop << ")\n";
    os << "-- client groups --\n";
    for (size_t gi = 0; gi < cfg_.cli_group.size(); ++gi) {
      auto& g = cfg_.cli_group[gi];
      uint64_t ops = 0, gres = 0, gprop = 0;
      int64_t finish = 0;
      int count = 0;
      for (int c = 0; c < n_clients_; ++c) {
        if (client_group_of_[c] != static_cast<int>(gi)) continue;
        ++count;
        ops += clients_[c]->stats.ops_completed;
        gres += clients_[c]->stats.reservation_ops;
        gprop += clients_[c]->stats.priority_ops;
        if (clients_[c]->stats.finish_time_ns > finish)
          finish = clients_[c]->stats.finish_time_ns;
      }
      double fin_s = finish / double(NS_PER_SEC);
      double rate = fin_s > 0 ? ops / fin_s : 0.0;
      snprintf(buf, sizeof buf,
               "group %zu: %d clients  r=%g w=%g l=%g | ops %llu "
               "(res %llu / prop %llu) | done @ %.2fs | average %.2f "
               "ops/s\n",
               gi, count, g.client_reservation, g.client_weight,
               g.client_limit, (unsigned long long)ops,
               (unsigned long long)gres, (unsigned long long)gprop, fin_s,
               rate);
      os << buf;
    }
    dmclock::ProfileCombiner add_t, gr_t, tr_t;
    for (auto& s : servers_) add_t.combine(s->stats.add_request_timer);
    for (auto& c : clients_) {
      gr_t.combine(c->stats.get_req_params_timer);
      tr_t.combine(c->stats.track_resp_timer);
    }
    os << "-- server internal stats --\n";
    snprintf(buf, sizeof buf, "average add_request: %.0f ns\n",
             add_t.mean_ns());
    os << buf;
    os << "-- client internal stats --\n";
    snprintf(buf, sizeof buf, "average get_req_params: %.0f ns\n",
             gr_t.mean_ns());
    os << buf;
    snprintf(buf, sizeof buf, "average track_resp: %.0f ns\n",
             tr_t.mean_ns());
    os << buf;
    if (show_intervals) {
      os << "-- per-client interval ops/sec --\n";
      for (int c = 0; c < n_clients_; ++c) {
        auto& times = clients_[c]->stats.completion_times_ns;
        os << "client " << c << ":";
        if (!times.empty()) {
          int64_t hi = 0;
          for (auto t : times)
            if (t > hi) hi = t;
          std::vector<int> buckets(hi / NS_PER_SEC + 1, 0);
          for (auto t : times) ++buckets[t / NS_PER_SEC];
          for (int b : buckets) os << " " << b;
        }
        os << "\n";
      }
    }
    return os.str();
  }

  int64_t virtual_now_ns() const { return loop_.now_ns; }
  double wall_seconds() const { return wall_seconds_; }
  uint64_t total_ops() const {
    uint64_t t = 0;
    for (auto& c : clients_) t += c->stats.ops_completed;
    return t;
  }

  std::vector<TraceOp> trace;

 private:
  // (harness.py _make_server_select; reference simulate.h:398-444)
  std::function<ServerId(int)> make_server_select(int client_idx,
                                                  const ClientGroup& g) {
    int servers_per = g.client_server_select_range < n_servers_
                          ? g.client_server_select_range
                          : n_servers_;
    double factor = double(n_servers_) / (n_clients_ > 1 ? n_clients_ : 1);
    if (cfg_.server_random_selection) {
      return [this, client_idx, servers_per, factor](int) -> ServerId {
        uint32_t offset = rng_.randrange(servers_per);
        return (static_cast<int64_t>(0.5 + client_idx * factor) + offset) %
               n_servers_;
      };
    }
    return [this, client_idx, servers_per, factor](int seed) -> ServerId {
      int offset = seed % servers_per;
      return (static_cast<int64_t>(0.5 + client_idx * factor) + offset) %
             n_servers_;
    };
  }

  SimConfig cfg_;
  EventLoop loop_;
  PyMT19937 rng_;
  std::vector<int> client_group_of_;
  std::vector<int> server_group_of_;
  std::vector<dmclock::ClientInfo> infos_;
  std::vector<std::unique_ptr<ISimServer>> servers_;
  PushQueueFactory push_queue_factory_;
  std::vector<std::unique_ptr<SimulatedClient<Tracker>>> clients_;
  std::set<ClientId> done_;
  std::vector<TraceOp>* trace_ptr_ = nullptr;
  int n_clients_ = 0;
  int n_servers_ = 0;
  double wall_seconds_ = 0.0;
};

}  // namespace qos_sim
