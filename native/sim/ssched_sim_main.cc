// ssched_sim -- native FIFO-baseline simulator binary.
//
// Equivalent of the reference's ssched_sim
// (/root/reference/sim/src/test_ssched_main.cc:49-199): the same
// discrete-event harness over the SimpleQueue FIFO + no-op tracker,
// used as the comparison baseline for the dmClock scheduler.  Unlike
// the reference binary (hardcoded parameters) this accepts the same
// config format as dmc_sim, mirroring the Python ssched_sim CLI.

#include <cstdio>
#include <cstring>
#include <memory>
#include <string>

#include "sim_harness.h"
#include "ssched.h"

int main(int argc, char** argv) {
  std::string conf;
  uint64_t seed = 12345;
  bool intervals = false;
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "-c") || !strcmp(argv[i], "--conf")) {
      if (++i >= argc) return 2;
      conf = argv[i];
    } else if (!strcmp(argv[i], "--seed")) {
      if (++i >= argc) return 2;
      seed = strtoull(argv[i], nullptr, 10);
    } else if (!strcmp(argv[i], "--intervals")) {
      intervals = true;
    } else {
      fprintf(stderr, "usage: %s -c CONF [--seed N] [--intervals]\n",
              argv[0]);
      return 2;
    }
  }

  qos_sim::SimConfig cfg;
  if (!conf.empty()) {
    try {
      cfg = qos_sim::parse_config_file(conf);
    } catch (const std::exception& e) {
      fprintf(stderr, "ssched_sim: %s\n", e.what());
      return 2;
    }
  } else {
    cfg.fill_defaults();
  }

  qos_sim::Simulation<qos_sim::SimpleQueue, qos_sim::NullServiceTracker>
      sim(
          cfg,
          [](qos_sim::ServerId,
             std::function<dmclock::ClientInfo(const qos_sim::ClientId&)>,
             int64_t, bool) { return std::make_unique<qos_sim::SimpleQueue>(); },
          [] { return std::make_unique<qos_sim::NullServiceTracker>(); },
          seed, false);
  sim.run();
  printf("%s", sim.report(intervals).c_str());
  return 0;
}
