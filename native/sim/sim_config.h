// Sim configuration: INI-compatible with the reference and with the
// Python sim (dmclock_tpu/sim/config.py; reference sim/src/config.h:32-155
// + config.cc:123-184).  Same sections ([global], [client.N],
// [server.N]), same keys, same defaults.

#pragma once

#include <algorithm>
#include <cctype>
#include <fstream>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace qos_sim {

struct ClientGroup {
  int client_count = 100;
  double client_wait_s = 0.0;
  int client_total_ops = 1000;
  int client_server_select_range = 10;
  double client_iops_goal = 50.0;
  int client_outstanding_ops = 100;
  double client_reservation = 20.0;
  double client_limit = 60.0;
  double client_weight = 1.0;
  int client_req_cost = 1;
};

struct ServerGroup {
  int server_count = 100;
  double server_iops = 40.0;
  int server_threads = 1;
};

struct SimConfig {
  int server_groups = 1;
  int client_groups = 1;
  bool server_random_selection = false;
  bool server_soft_limit = true;
  double anticipation_timeout_s = 0.0;
  std::vector<ClientGroup> cli_group;
  std::vector<ServerGroup> srv_group;

  void fill_defaults() {
    while (static_cast<int>(cli_group.size()) < client_groups)
      cli_group.emplace_back();
    while (static_cast<int>(srv_group.size()) < server_groups)
      srv_group.emplace_back();
  }

  int total_clients() const {
    int n = 0;
    for (auto& g : cli_group) n += g.client_count;
    return n;
  }
  int total_servers() const {
    int n = 0;
    for (auto& g : srv_group) n += g.server_count;
    return n;
  }
};

namespace detail {

inline std::string trim(const std::string& s) {
  size_t a = s.find_first_not_of(" \t\r\n");
  if (a == std::string::npos) return "";
  size_t b = s.find_last_not_of(" \t\r\n");
  return s.substr(a, b - a + 1);
}

inline bool to_bool(const std::string& v, bool dflt) {
  if (v.empty()) return dflt;
  std::string lo = v;
  std::transform(lo.begin(), lo.end(), lo.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return lo == "1" || lo == "true" || lo == "yes" || lo == "on";
}

using Section = std::map<std::string, std::string>;

inline std::map<std::string, Section> parse_ini(const std::string& path) {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot read config file: " + path);
  std::map<std::string, Section> out;
  std::string line, section;
  while (std::getline(f, line)) {
    line = trim(line);
    if (line.empty() || line[0] == '#' || line[0] == ';') continue;
    if (line.front() == '[' && line.back() == ']') {
      section = trim(line.substr(1, line.size() - 2));
      out[section];
      continue;
    }
    size_t eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string key = trim(line.substr(0, eq));
    std::string val = trim(line.substr(eq + 1));
    // strip trailing inline comments
    size_t h = val.find_first_of("#;");
    if (h != std::string::npos) val = trim(val.substr(0, h));
    out[section][key] = val;
  }
  return out;
}

inline const std::string* find(const std::map<std::string, Section>& ini,
                               const std::string& sec,
                               const std::string& key) {
  auto s = ini.find(sec);
  if (s == ini.end()) return nullptr;
  auto k = s->second.find(key);
  if (k == s->second.end()) return nullptr;
  return &k->second;
}

inline int geti(const std::map<std::string, Section>& ini,
                const std::string& sec, const std::string& key, int d) {
  auto* v = find(ini, sec, key);
  return v ? std::stoi(*v) : d;
}
inline double getd(const std::map<std::string, Section>& ini,
                   const std::string& sec, const std::string& key,
                   double d) {
  auto* v = find(ini, sec, key);
  return v ? std::stod(*v) : d;
}
inline bool getb(const std::map<std::string, Section>& ini,
                 const std::string& sec, const std::string& key, bool d) {
  auto* v = find(ini, sec, key);
  return v ? to_bool(*v, d) : d;
}

}  // namespace detail

inline SimConfig parse_config_file(const std::string& path) {
  using namespace detail;
  auto ini = parse_ini(path);
  SimConfig cfg;
  cfg.server_groups = geti(ini, "global", "server_groups", 1);
  cfg.client_groups = geti(ini, "global", "client_groups", 1);
  cfg.server_random_selection =
      getb(ini, "global", "server_random_selection", false);
  cfg.server_soft_limit = getb(ini, "global", "server_soft_limit", true);
  cfg.anticipation_timeout_s =
      getd(ini, "global", "anticipation_timeout", 0.0);

  for (int i = 0; i < cfg.client_groups; ++i) {
    std::string sec = "client." + std::to_string(i);
    ClientGroup d;
    ClientGroup g;
    g.client_count = geti(ini, sec, "client_count", d.client_count);
    g.client_wait_s = getd(ini, sec, "client_wait", d.client_wait_s);
    g.client_total_ops =
        geti(ini, sec, "client_total_ops", d.client_total_ops);
    g.client_server_select_range = geti(
        ini, sec, "client_server_select_range", d.client_server_select_range);
    g.client_iops_goal =
        getd(ini, sec, "client_iops_goal", d.client_iops_goal);
    g.client_outstanding_ops =
        geti(ini, sec, "client_outstanding_ops", d.client_outstanding_ops);
    g.client_reservation =
        getd(ini, sec, "client_reservation", d.client_reservation);
    g.client_limit = getd(ini, sec, "client_limit", d.client_limit);
    g.client_weight = getd(ini, sec, "client_weight", d.client_weight);
    g.client_req_cost = geti(ini, sec, "client_req_cost", d.client_req_cost);
    cfg.cli_group.push_back(g);
  }
  for (int i = 0; i < cfg.server_groups; ++i) {
    std::string sec = "server." + std::to_string(i);
    ServerGroup d;
    ServerGroup g;
    g.server_count = geti(ini, sec, "server_count", d.server_count);
    g.server_iops = getd(ini, sec, "server_iops", d.server_iops);
    g.server_threads = geti(ini, sec, "server_threads", d.server_threads);
    cfg.srv_group.push_back(g);
  }
  cfg.fill_defaults();
  return cfg;
}

}  // namespace qos_sim
