// C ABI over the native dmClock runtime.
//
// Exposes the Pull queue and ServiceTracker with integer client/request
// handles so Python (ctypes) and other embedders can drive the C++
// scheduler -- the framework's fast CPU backend and the cross-language
// golden-parity surface (python tests compare its decision stream
// bit-for-bit with the Python oracle and the TPU engine).
//
// QoS parameters are registered per client id up front (or updated
// later), playing the role of the reference's ClientInfoFunc callback
// seam (dmclock_server.h:542) without cross-language calls per request.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <unordered_map>

#include "dmclock/scheduler.h"
#include "dmclock/tracker.h"

using dmclock::AtLimit;
using dmclock::ClientInfo;
using dmclock::Cost;
using dmclock::Phase;
using dmclock::ReqParams;
using dmclock::TimeNs;

namespace {

using Queue = dmclock::PullPriorityQueue<uint64_t, uint64_t>;

struct QueueHandle {
  std::unordered_map<uint64_t, ClientInfo> infos;
  std::mutex info_mtx;
  std::unique_ptr<Queue> queue;
  bool fake_clock_set = false;
  double fake_now_s = 0.0;
};

}  // namespace

extern "C" {

// ABI version: bump on ANY signature/semantic change.  The ctypes
// loader refuses a stale prebuilt .so (a 6-arg dmc_queue_create would
// silently ignore a 7th argument per the calling convention).
int dmc_capi_version(void) { return 2; }

// ---- queue ----------------------------------------------------------

void* dmc_queue_create(int delayed_tag_calc, int at_limit,
                       int64_t reject_threshold_ns,
                       int64_t anticipation_timeout_ns,
                       unsigned heap_branching, int dynamic_cli_info,
                       int use_prop_heap, double idle_age_s,
                       double erase_age_s, double check_time_s,
                       uint64_t erase_max) {
  auto* h = new QueueHandle();
  Queue::Options opt;
  opt.delayed_tag_calc = delayed_tag_calc != 0;
  opt.at_limit = static_cast<AtLimit>(at_limit);
  opt.reject_threshold_ns = reject_threshold_ns;
  opt.anticipation_timeout_ns = anticipation_timeout_ns;
  opt.heap_branching = heap_branching;
  opt.dynamic_cli_info = dynamic_cli_info != 0;
  opt.use_prop_heap = use_prop_heap != 0;
  if (idle_age_s > 0) opt.idle_age_s = idle_age_s;
  if (erase_age_s > 0) opt.erase_age_s = erase_age_s;
  if (check_time_s > 0) opt.check_time_s = check_time_s;
  if (erase_max > 0) opt.erase_max = erase_max;
  opt.run_gc_thread = false;  // GC driven via dmc_queue_do_clean
  h->queue = std::make_unique<Queue>(
      [h](const uint64_t& c) {
        std::lock_guard<std::mutex> g(h->info_mtx);
        auto it = h->infos.find(c);
        if (it == h->infos.end()) {
          // fail loudly: the Python oracle asserts on missing info, and
          // a silent default would break cross-backend parity
          fprintf(stderr,
                  "dmclock capi: no ClientInfo registered for client "
                  "%llu (call dmc_queue_set_client_info first)\n",
                  static_cast<unsigned long long>(c));
          abort();
        }
        return it->second;
      },
      opt);
  return h;
}

void dmc_queue_destroy(void* q) { delete static_cast<QueueHandle*>(q); }

void dmc_queue_set_client_info(void* q, uint64_t client, double r,
                               double w, double l) {
  auto* h = static_cast<QueueHandle*>(q);
  std::lock_guard<std::mutex> g(h->info_mtx);
  h->infos[client].update(r, w, l);
}

void dmc_queue_update_client_info(void* q, uint64_t client) {
  static_cast<QueueHandle*>(q)->queue->update_client_info(client);
}

int dmc_queue_add(void* q, uint64_t client, uint64_t req_id,
                  uint32_t delta, uint32_t rho, int64_t time_ns,
                  uint32_t cost) {
  return static_cast<QueueHandle*>(q)->queue->add_request(
      req_id, client, ReqParams(delta, rho), time_ns, cost);
}

// returns NextReqType (0 returning / 1 future / 2 none); fills outputs
int dmc_queue_pull(void* q, int64_t now_ns, uint64_t* client,
                   uint64_t* req_id, int* phase, uint32_t* cost,
                   int64_t* when_ready) {
  auto pr = static_cast<QueueHandle*>(q)->queue->pull_request(now_ns);
  if (pr.is_retn()) {
    *client = pr.client;
    *req_id = pr.request;
    *phase = static_cast<int>(pr.phase);
    *cost = pr.cost;
  } else if (pr.is_future()) {
    *when_ready = pr.when_ready;
  }
  return static_cast<int>(pr.type);
}

uint64_t dmc_queue_request_count(void* q) {
  return static_cast<QueueHandle*>(q)->queue->request_count();
}
uint64_t dmc_queue_client_count(void* q) {
  return static_cast<QueueHandle*>(q)->queue->client_count();
}
int dmc_queue_empty(void* q) {
  return static_cast<QueueHandle*>(q)->queue->empty() ? 1 : 0;
}

void dmc_queue_counters(void* q, uint64_t* reserv, uint64_t* prop,
                        uint64_t* limit_break) {
  auto* h = static_cast<QueueHandle*>(q);
  *reserv = h->queue->reserv_sched_count;
  *prop = h->queue->prop_sched_count;
  *limit_break = h->queue->limit_break_sched_count;
}

// removed request ids are written into out[] (capacity cap); returns
// the number removed
uint64_t dmc_queue_remove_by_client(void* q, uint64_t client,
                                    int reverse, uint64_t* out,
                                    uint64_t cap) {
  uint64_t n = 0;
  static_cast<QueueHandle*>(q)->queue->remove_by_client(
      client, reverse != 0, [&](uint64_t&& r) {
        if (n < cap) out[n] = r;
        ++n;
      });
  return n;
}

void dmc_queue_do_clean(void* q) {
  static_cast<QueueHandle*>(q)->queue->do_clean();
}

// deterministic GC clock injection (the C++ set_monotonic_clock made
// ABI-visible so differential tests can drive idle-marking exactly
// like the oracle's injected monotonic_clock)
void dmc_queue_set_fake_clock(void* q, double now_s) {
  auto* h = static_cast<QueueHandle*>(q);
  if (!h->fake_clock_set) {
    h->fake_clock_set = true;
    h->queue->set_monotonic_clock([h] { return h->fake_now_s; });
  }
  h->fake_now_s = now_s;
}

unsigned dmc_queue_heap_branching(void* q) {
  return static_cast<QueueHandle*>(q)->queue->get_heap_branching_factor();
}

// ---- tracker --------------------------------------------------------

void* dmc_tracker_create(int borrowing) {
  if (borrowing)
    return new dmclock::ServiceTracker<uint64_t, dmclock::BorrowingTracker>();
  return new dmclock::ServiceTracker<uint64_t>();
}

// `borrowing` must match the create call (selects the concrete type)
void dmc_tracker_destroy(void* t, int borrowing) {
  if (borrowing)
    delete static_cast<
        dmclock::ServiceTracker<uint64_t, dmclock::BorrowingTracker>*>(t);
  else
    delete static_cast<dmclock::ServiceTracker<uint64_t>*>(t);
}

void dmc_tracker_track_resp(void* t, int borrowing, uint64_t server,
                            int phase, uint32_t cost) {
  if (borrowing)
    static_cast<
        dmclock::ServiceTracker<uint64_t, dmclock::BorrowingTracker>*>(t)
        ->track_resp(server, static_cast<Phase>(phase), cost);
  else
    static_cast<dmclock::ServiceTracker<uint64_t>*>(t)->track_resp(
        server, static_cast<Phase>(phase), cost);
}

void dmc_tracker_get_req_params(void* t, int borrowing, uint64_t server,
                                uint32_t* delta, uint32_t* rho) {
  ReqParams rp =
      borrowing
          ? static_cast<dmclock::ServiceTracker<
                uint64_t, dmclock::BorrowingTracker>*>(t)
                ->get_req_params(server)
          : static_cast<dmclock::ServiceTracker<uint64_t>*>(t)
                ->get_req_params(server);
  *delta = rp.delta;
  *rho = rp.rho;
}

}  // extern "C"
